"""Serving-plane load generator — closed- and open-loop traffic against
a ServingEngine, in-process or over the HTTP ingress
(docs/SERVING.md "Bench methodology" + "Ingress & overload").

Library (bench.py + tests/test_serving*.py import these):
  * ``run_closed_loop(predict, feeds, clients, duration_s)`` — N client
    threads, each submits its next request the moment the previous one
    completes (throughput-under-concurrency; latency EXCLUDES client
    think time). The shape bench.py's serving lanes measure.
  * ``run_open_loop(submit, feeds, rate_qps, duration_s)`` — one pacing
    thread fires async submits on a fixed-rate schedule regardless of
    completions (latency-under-load; queueing delay INCLUDED — the
    number a p99 SLO is about). Reports ``behind`` when the pacer
    cannot hold the target rate.
  * ``HttpClient`` / ``run_http_closed_loop`` / ``run_http_open_loop``
    — the same two disciplines through a live ``ServingIngress``,
    classifying statuses (200/429/504/5xx) instead of raising: under
    deliberate overload a typed shed is a RESULT, not an error.
  * ``run_overload_scenario`` — measures 1× HTTP capacity closed-loop,
    then drives open-loop at 1× and ``overload_factor``× and reports
    accepted-request p99s, shed/expired counts, and the "every
    non-accepted request answered typed" check.
  * ``run_chaos_scenario`` — kills a pserver mid-HTTP-serving and
    reports degraded (stale-cache) responses, 5xx counts for
    cache-covered rows, and recovery after a PR 6-style promotion.
  * ``run_http_fleet_closed_loop`` / ``run_http_fleet_open_loop`` —
    the same two disciplines spread over a serving FLEET via
    ``serving.FleetRouter`` (round-robin + retry-on-503/reset, live
    directory view), reporting a per-endpoint status/latency breakdown
    and the reroute count (docs/SERVING.md "Fleet").
  * ``start_inproc_pserver`` / ``push_table`` — the in-process
    listen_and_serv harness the serving PS lanes and tests run against
    (same shape as tests/test_ps_membership.py's protocol harness).

CLI (manual runs)::

    JAX_PLATFORMS=cpu python tools/serving_loadgen.py \
        --clients 16 --duration 3 --max-batch 16 --mode closed
    python tools/serving_loadgen.py --mode open --rate 500 --naive
    python tools/serving_loadgen.py --mode http                 # closed over HTTP
    python tools/serving_loadgen.py --mode http --scenario overload
    python tools/serving_loadgen.py --mode http --scenario chaos
    python tools/serving_loadgen.py --mode http \
        --endpoints 127.0.0.1:8801,127.0.0.1:8802   # fleet round-robin
    python tools/serving_loadgen.py --mode http --directory 127.0.0.1:8700 \
        --fleet-loop open --rate 300                # follow the live view

Prints one JSON line: loadgen results + the engine's stats() surface
(including the shed / deadline_expired / degraded / breaker_open
overload counters).
"""
from __future__ import annotations

import argparse
import http.client
import json
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _percentiles(lats_s: Sequence[float]) -> Dict[str, float]:
    from paddle_tpu.serving.engine import percentiles_ms
    return percentiles_ms(lats_s, suffix="_ms")


def run_closed_loop(predict: Callable[[dict], object],
                    feeds: Sequence[dict], clients: int = 16,
                    duration_s: float = 3.0,
                    warmup_s: float = 0.5) -> Dict[str, float]:
    """Closed loop: ``clients`` threads call ``predict(feed)`` back to
    back for ``duration_s`` (after ``warmup_s`` whose samples are
    discarded — first-touch compiles and cold caches must not land in
    the percentiles). Returns qps + latency percentiles over the
    measured window."""
    results: List[List] = [[] for _ in range(clients)]
    errors: List[BaseException] = []
    go = threading.Event()
    t_box = {}

    def worker(wid: int):
        rs = results[wid]
        go.wait()
        end = t_box["t0"] + warmup_s + duration_s
        i = wid
        while time.perf_counter() < end:
            feed = feeds[i % len(feeds)]
            i += clients
            t = time.perf_counter()
            try:
                predict(feed)
            except BaseException as e:  # surface, don't hang the join
                errors.append(e)
                return
            rs.append((time.perf_counter(), t))

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(clients)]
    for t in threads:
        t.start()
    t_box["t0"] = time.perf_counter()
    go.set()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    cut = t_box["t0"] + warmup_s
    done = sorted((td, td - ts) for rs in results for td, ts in rs
                  if ts >= cut)
    if not done:
        return {"qps": 0.0, "n": 0, "clients": clients,
                **_percentiles([])}
    span = done[-1][0] - cut
    out = {"qps": len(done) / span if span > 1e-9 else 0.0,
           "n": len(done), "clients": clients,
           "duration_s": round(span, 3)}
    out.update(_percentiles([lat for _t, lat in done]))
    return out


def run_open_loop(submit: Callable[[dict], object], feeds: Sequence[dict],
                  rate_qps: float, duration_s: float = 3.0,
                  timeout_s: float = 120.0) -> Dict[str, float]:
    """Open loop: submit async requests at ``rate_qps`` for
    ``duration_s``; latency = submit→fulfilment (futures must expose
    ``.wait(timeout)`` and ``.t_submit``/``.t_done`` stamps — the
    serving Request contract). ``behind`` counts schedule slots the
    pacer missed (the engine saturated: achieved rate < target)."""
    if rate_qps <= 0:
        raise ValueError("rate_qps must be > 0")
    period = 1.0 / float(rate_qps)
    futs = []
    behind = 0
    start = time.perf_counter()
    next_t = start
    i = 0
    while True:
        now = time.perf_counter()
        if now >= start + duration_s:
            break
        if now < next_t:
            time.sleep(next_t - now)
        fut = submit(feeds[i % len(feeds)])
        futs.append(fut)
        i += 1
        next_t += period
        if time.perf_counter() > next_t + period:
            behind += 1
    for f in futs:
        f.wait(timeout_s)
    lats = [f.t_done - f.t_submit for f in futs]
    span = (max(f.t_done for f in futs) - start) if futs else 0.0
    out = {"target_qps": float(rate_qps),
           "qps": len(futs) / span if span > 1e-9 else 0.0,
           "n": len(futs), "behind": behind,
           "duration_s": round(span, 3)}
    out.update(_percentiles(lats))
    return out


# ------------------------------------------------------------------ HTTP
class HttpClient:
    """One keep-alive connection to a ServingIngress; reconnects once
    on transport failure (a drained server sends Connection: close —
    the next call must not die on the stale socket). ``predict``
    returns ``(status, body_dict)`` instead of raising on 4xx/5xx:
    under deliberate overload a typed shed is a RESULT to count."""

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self.host, self.port, self.timeout = host, int(port), timeout
        self._conn: Optional[http.client.HTTPConnection] = None

    def _request(self, method: str, path: str, body=None, headers=None):
        last = None
        for attempt in (0, 1):
            try:
                if self._conn is None:
                    self._conn = http.client.HTTPConnection(
                        self.host, self.port, timeout=self.timeout)
                self._conn.request(method, path, body=body,
                                   headers=headers or {})
                r = self._conn.getresponse()
                data = r.read()
                if r.will_close:
                    self._conn.close()
                    self._conn = None
                try:
                    obj = json.loads(data) if data else {}
                except ValueError:
                    obj = {"raw": data.decode("utf-8", "replace")}
                return r.status, r, obj
            except (http.client.HTTPException, OSError) as e:
                last = e
                if self._conn is not None:
                    try:
                        self._conn.close()
                    except OSError:
                        pass
                    self._conn = None
        raise last

    def predict(self, feed: dict, model: Optional[str] = None,
                deadline_ms: Optional[float] = None, many: bool = False,
                extra_headers: Optional[dict] = None):
        path = ("/predict" if model is None
                else f"/models/{model}/predict")
        body = json.dumps({
            "feed": {k: (np.asarray(v).tolist()) for k, v in feed.items()},
            "many": many})
        headers = {"Content-Type": "application/json"}
        if deadline_ms is not None:
            headers["X-Deadline-Ms"] = str(float(deadline_ms))
        if extra_headers:
            headers.update(extra_headers)
        status, _r, obj = self._request("POST", path, body, headers)
        return status, obj

    def get(self, path: str):
        status, _r, obj = self._request("GET", path)
        return status, obj

    def close(self):
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None


def _status_key(status: int) -> str:
    if status == 200:
        return "ok"
    if status in (429, 503, 504):
        return str(status)
    return "5xx" if status >= 500 else str(status)


def run_http_closed_loop(host: str, port: int, feeds: Sequence[dict],
                         clients: int = 16, duration_s: float = 3.0,
                         warmup_s: float = 0.5,
                         deadline_ms: Optional[float] = None,
                         model: Optional[str] = None) -> Dict[str, float]:
    """Closed loop over the HTTP ingress: qps/percentiles of ACCEPTED
    (200) responses + a status histogram. Non-200s don't stop a client
    — they count."""
    results: List[List] = [[] for _ in range(clients)]
    counts: List[Dict[str, int]] = [{} for _ in range(clients)]
    degraded = [0] * clients
    go = threading.Event()
    t_box = {}

    def worker(wid: int):
        cli = HttpClient(host, port)
        rs = results[wid]
        cs = counts[wid]
        go.wait()
        end = t_box["t0"] + warmup_s + duration_s
        i = wid
        while time.perf_counter() < end:
            feed = feeds[i % len(feeds)]
            i += clients
            t = time.perf_counter()
            try:
                status, obj = cli.predict(feed, model=model,
                                          deadline_ms=deadline_ms)
            except OSError:
                cs["transport"] = cs.get("transport", 0) + 1
                continue
            key = _status_key(status)
            cs[key] = cs.get(key, 0) + 1
            if status == 200:
                rs.append((time.perf_counter(), t))
                if obj.get("degraded"):
                    degraded[wid] += 1
        cli.close()

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(clients)]
    for t in threads:
        t.start()
    t_box["t0"] = time.perf_counter()
    go.set()
    for t in threads:
        t.join()
    cut = t_box["t0"] + warmup_s
    done = sorted((td, td - ts) for rs in results for td, ts in rs
                  if ts >= cut)
    hist: Dict[str, int] = {}
    for cs in counts:
        for k, v in cs.items():
            hist[k] = hist.get(k, 0) + v
    span = (done[-1][0] - cut) if done else 0.0
    out = {"qps": len(done) / span if span > 1e-9 else 0.0,
           "n_ok": len(done), "clients": clients,
           "statuses": dict(sorted(hist.items())),
           "degraded_ok": int(sum(degraded)),
           "duration_s": round(span, 3)}
    out.update(_percentiles([lat for _t, lat in done]))
    return out


def run_http_open_loop(host: str, port: int, feeds: Sequence[dict],
                       rate_qps: float, duration_s: float = 3.0,
                       clients: int = 16,
                       deadline_ms: Optional[float] = None,
                       model: Optional[str] = None) -> Dict[str, float]:
    """Open loop over HTTP: a pacer schedules requests at ``rate_qps``
    regardless of completions; ``clients`` sender threads carry them.
    Latency is scheduled-time → response (client-side queueing counts
    against the server — the SLO view). This only holds the offered
    rate if the server answers FAST (accepted or typed-shed): senders
    blocked past their slot surface as ``behind``."""
    import queue as _queue

    if rate_qps <= 0:
        raise ValueError("rate_qps must be > 0")
    period = 1.0 / float(rate_qps)
    q: "_queue.Queue" = _queue.Queue()
    # accepted (200) latencies, BOTH clocks: from request send (what
    # the SERVER did to the request — the accepted-p99 contract) and
    # from the pacing schedule (includes client-side sender queueing:
    # honest about coordinated omission, but on a closed sender pool
    # at deliberate overload it measures the harness, not the server —
    # `behind` carries that debt explicitly)
    acc: List[tuple] = []       # (lat_from_send, lat_from_sched)
    hist: Dict[str, int] = {}
    degraded = [0]
    behind = [0]
    lock = threading.Lock()

    def sender():
        cli = HttpClient(host, port)
        while True:
            item = q.get()
            if item is None:
                break
            t_sched, feed = item
            t_start = time.perf_counter()
            if t_start > t_sched + period:
                with lock:
                    behind[0] += 1
            try:
                status, obj = cli.predict(feed, model=model,
                                          deadline_ms=deadline_ms)
            except OSError:
                with lock:
                    hist["transport"] = hist.get("transport", 0) + 1
                continue
            t_done = time.perf_counter()
            with lock:
                key = _status_key(status)
                hist[key] = hist.get(key, 0) + 1
                if status == 200:
                    acc.append((t_done - t_start, t_done - t_sched))
                    if obj.get("degraded"):
                        degraded[0] += 1
        cli.close()

    senders = [threading.Thread(target=sender, daemon=True)
               for _ in range(clients)]
    for t in senders:
        t.start()
    start = time.perf_counter()
    next_t = start
    i = 0
    while time.perf_counter() < start + duration_s:
        now = time.perf_counter()
        if now < next_t:
            time.sleep(min(next_t - now, 0.05))
            continue
        q.put((next_t, feeds[i % len(feeds)]))
        i += 1
        next_t += period
    for _ in senders:
        q.put(None)
    for t in senders:
        t.join()
    n_offered = i
    out = {"target_qps": float(rate_qps), "offered": n_offered,
           "accepted": len(acc),
           "accepted_rate": len(acc) / max(n_offered, 1),
           "behind": behind[0], "clients": clients,
           "statuses": dict(sorted(hist.items())),
           "degraded_ok": degraded[0]}
    out.update(_percentiles([lat for lat, _s in acc]))
    sched = _percentiles([s for _lat, s in acc])
    out.update({f"sched_{k}": v for k, v in sched.items()})
    return out


# ----------------------------------------------------------- fleet loops
def _fleet_router(endpoints, directory_ep, timeout_s=60.0):
    from paddle_tpu.serving import FleetRouter

    return FleetRouter(directory_ep=directory_ep,
                       endpoints=endpoints or None, timeout_s=timeout_s)


def _merge_by_endpoint(routers) -> Dict[str, Dict[str, float]]:
    """Aggregate the per-worker routers' per-endpoint breakdowns into
    one table with derived mean latency — the multi-endpoint report
    (docs/SERVING.md "Fleet") that shows WHERE the 503s/resets landed
    and that the retried requests were absorbed elsewhere."""
    agg: Dict[str, Dict[str, float]] = {}
    for r in routers:
        for ep, d in r.stats()["by_endpoint"].items():
            a = agg.setdefault(ep, {})
            for k, v in d.items():
                a[k] = a.get(k, 0) + v
    for d in agg.values():
        n = d.pop("lat_n", 0)
        s = d.pop("lat_sum_ms", 0.0)
        if n:
            d["lat_mean_ms"] = round(s / n, 3)
    return {ep: dict(sorted(d.items())) for ep, d in sorted(agg.items())}


def run_http_fleet_closed_loop(endpoints: Sequence[str], feeds,
                               clients: int = 16, duration_s: float = 3.0,
                               warmup_s: float = 0.5,
                               deadline_ms: Optional[float] = None,
                               model: Optional[str] = None,
                               directory_ep: Optional[str] = None
                               ) -> Dict[str, float]:
    """Closed loop spread over a serving FLEET: each client thread owns
    a ``FleetRouter`` (round-robin + retry across members on 503/
    connection-reset, live-view refresh when ``directory_ep`` is
    given). Reports the single-endpoint shape PLUS ``by_endpoint`` and
    ``reroutes`` — a rolling restart shows up as per-member 503 counts
    with zero client-visible failures."""
    from paddle_tpu.serving import NoLiveMembersError

    results: List[List] = [[] for _ in range(clients)]
    counts: List[Dict[str, int]] = [{} for _ in range(clients)]
    routers = [_fleet_router(list(endpoints), directory_ep)
               for _ in range(clients)]
    go = threading.Event()
    t_box = {}

    def worker(wid: int):
        router = routers[wid]
        rs, cs = results[wid], counts[wid]
        go.wait()
        end = t_box["t0"] + warmup_s + duration_s
        i = wid
        while time.perf_counter() < end:
            feed = feeds[i % len(feeds)]
            i += clients
            t = time.perf_counter()
            try:
                status, obj = router.predict(feed, model=model,
                                             deadline_ms=deadline_ms)
            except NoLiveMembersError:
                cs["no_live"] = cs.get("no_live", 0) + 1
                time.sleep(0.05)
                continue
            key = _status_key(status)
            cs[key] = cs.get(key, 0) + 1
            if status == 200:
                rs.append((time.perf_counter(), t))
        router.close()

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(clients)]
    for t in threads:
        t.start()
    t_box["t0"] = time.perf_counter()
    go.set()
    for t in threads:
        t.join()
    cut = t_box["t0"] + warmup_s
    done = sorted((td, td - ts) for rs in results for td, ts in rs
                  if ts >= cut)
    hist: Dict[str, int] = {}
    for cs in counts:
        for k, v in cs.items():
            hist[k] = hist.get(k, 0) + v
    span = (done[-1][0] - cut) if done else 0.0
    out = {"qps": len(done) / span if span > 1e-9 else 0.0,
           "n_ok": len(done), "clients": clients,
           "statuses": dict(sorted(hist.items())),
           "reroutes": int(sum(r.stats()["reroutes"] for r in routers)),
           "by_endpoint": _merge_by_endpoint(routers),
           "duration_s": round(span, 3)}
    out.update(_percentiles([lat for _t, lat in done]))
    return out


def run_http_fleet_open_loop(endpoints: Sequence[str], feeds,
                             rate_qps: float, duration_s: float = 3.0,
                             clients: int = 16,
                             deadline_ms: Optional[float] = None,
                             model: Optional[str] = None,
                             directory_ep: Optional[str] = None
                             ) -> Dict[str, float]:
    """Open loop over a fleet: same pacer/sender-pool contract as
    ``run_http_open_loop`` (scheduled-time latency, ``behind`` debt)
    with the routing layer of the closed-loop variant — the chaos
    scenario's load shape (a kill mid-run must NOT dent the accepted
    rate beyond the retried requests' extra hop)."""
    import queue as _queue

    from paddle_tpu.serving import NoLiveMembersError

    if rate_qps <= 0:
        raise ValueError("rate_qps must be > 0")
    period = 1.0 / float(rate_qps)
    q: "_queue.Queue" = _queue.Queue()
    acc: List[tuple] = []
    hist: Dict[str, int] = {}
    behind = [0]
    lock = threading.Lock()
    routers = [_fleet_router(list(endpoints), directory_ep)
               for _ in range(clients)]

    def sender(wid: int):
        router = routers[wid]
        while True:
            item = q.get()
            if item is None:
                break
            t_sched, feed = item
            t_start = time.perf_counter()
            if t_start > t_sched + period:
                with lock:
                    behind[0] += 1
            try:
                status, obj = router.predict(feed, model=model,
                                             deadline_ms=deadline_ms)
            except NoLiveMembersError:
                with lock:
                    hist["no_live"] = hist.get("no_live", 0) + 1
                continue
            t_done = time.perf_counter()
            with lock:
                key = _status_key(status)
                hist[key] = hist.get(key, 0) + 1
                if status == 200:
                    acc.append((t_done - t_start, t_done - t_sched))
        router.close()

    senders = [threading.Thread(target=sender, args=(w,), daemon=True)
               for w in range(clients)]
    for t in senders:
        t.start()
    start = time.perf_counter()
    next_t = start
    i = 0
    while time.perf_counter() < start + duration_s:
        now = time.perf_counter()
        if now < next_t:
            time.sleep(min(next_t - now, 0.05))
            continue
        q.put((next_t, feeds[i % len(feeds)]))
        i += 1
        next_t += period
    for _ in senders:
        q.put(None)
    for t in senders:
        t.join()
    n_offered = i
    out = {"target_qps": float(rate_qps), "offered": n_offered,
           "accepted": len(acc),
           "accepted_rate": len(acc) / max(n_offered, 1),
           "behind": behind[0], "clients": clients,
           "statuses": dict(sorted(hist.items())),
           "reroutes": int(sum(r.stats()["reroutes"] for r in routers)),
           "by_endpoint": _merge_by_endpoint(routers)}
    out.update(_percentiles([lat for lat, _s in acc]))
    sched = _percentiles([s for _lat, s in acc])
    out.update({f"sched_{k}": v for k, v in sched.items()})
    return out


# ------------------------------------------------------------------ harness
def start_inproc_pserver(endpoint: str, bind: str = "",
                         standby: bool = False,
                         pserver_endpoints: Sequence[str] = (),
                         sync: bool = False):
    """One in-process listen_and_serv loop on its own scope/thread —
    the serving PS lanes' pserver harness. Returns (thread, scope);
    stop with ``stop_inproc_pserver``."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core

    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        main.global_block().append_op(
            type="listen_and_serv", inputs={}, outputs={},
            attrs={"endpoint": endpoint, "sync_mode": sync,
                   "Fanin": 1, "optimize_blocks": [],
                   "grad_to_block_id": [],
                   "pserver_endpoints": list(pserver_endpoints)
                   or [endpoint],
                   "bind_endpoint": bind, "standby": standby,
                   "replica_of": ""})
    scope = core.Scope()
    exe = fluid.Executor()
    th = threading.Thread(
        target=lambda: exe.run(main, scope=scope, feed={},
                               fetch_list=[]), daemon=True)
    th.start()
    return th, scope


def stop_inproc_pserver(physical_ep: str, thread) -> None:
    from paddle_tpu.fluid.ps_rpc import VarClient
    try:
        c = VarClient(physical_ep, connect_timeout=5.0, channels=1,
                      resolve=False)
        c.stop()
        c.close()
    except Exception:
        pass
    thread.join(timeout=10)


def push_table(endpoints: Sequence[str], name: str,
               table: np.ndarray) -> None:
    """Install a full embedding table on every pserver (each serves its
    ``id %% n`` shard out of it; prefetch_rows indexes by GLOBAL id, so
    shipping the whole array keeps the harness trivially bit-equal to
    the local oracle)."""
    from paddle_tpu.fluid.ps_rpc import VarClient
    for ep in endpoints:
        c = VarClient(ep, connect_timeout=30.0, channels=1)
        c.send_var(name, np.asarray(table))
        c.close()


def free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def build_mlp_serving_model(n_feeds: int = 64):
    """The mnist-shaped serving model every mnist lane measures — ONE
    builder so the CLI loadgen and bench.py serve_mnist stay comparable
    by construction. Returns (program, scope, out_name, feeds) with
    params initialized and ``feeds`` a list of single-row feed dicts."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[784], dtype="float32")
        h = fluid.layers.fc(x, 256, act="relu")
        out = fluid.layers.fc(h, 10, act="softmax")
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    rng = np.random.RandomState(0)
    feeds = [{"x": rng.rand(784).astype(np.float32)}
             for _ in range(n_feeds)]
    return main, scope, out.name, feeds


# ------------------------------------------------------------- scenarios
def run_overload_scenario(clients: int = 16, duration_s: float = 2.0,
                          warmup_s: float = 0.5, max_batch: int = 16,
                          max_queue_rows: Optional[int] = None,
                          deadline_ms: float = 500.0,
                          overload_factor: float = 4.0,
                          workers: int = 2) -> Dict[str, object]:
    """The ISSUE 9 overload acceptance shape, as a library function
    (CLI ``--scenario overload`` and ``bench.py serve_http_overload``
    both run it): measure 1× capacity closed-loop over HTTP, then
    drive open-loop at 1× and ``overload_factor``×. Reports
    accepted-request p99 at both loads, the shed rate, the status
    histogram (every non-200 must be a TYPED 429/504/503 — "5xx"/
    "transport" entries are the failure signal), and the engine's
    shed/deadline_expired counters."""
    from paddle_tpu.serving import (AdmissionController, ServingEngine,
                                    ServingIngress)

    if max_queue_rows is None:
        # the admission bound must sit BELOW the sender pool's
        # concurrency or a closed pool of blocking clients caps the
        # server queue at `clients` rows and the bound never engages —
        # the 4× leg would measure client-side pacing debt, not
        # server-side shedding
        max_queue_rows = max(4, clients // 2)
    main, scope, out_name, feeds = build_mlp_serving_model()
    eng = ServingEngine(
        program=main, scope=scope, feed_names=["x"],
        fetch_names=[out_name], max_batch=max_batch,
        max_queue_delay_ms=2.0, num_workers=workers,
        admission=AdmissionController(max_queue_rows=max_queue_rows,
                                      codel_target_ms=deadline_ms / 4,
                                      codel_interval_ms=deadline_ms / 2))
    eng.warm()
    ing = ServingIngress({"mlp": eng},
                         default_deadline_ms=deadline_ms).start()
    host, port = "127.0.0.1", ing.port
    try:
        eng.reset_stats()
        closed = run_http_closed_loop(host, port, feeds,
                                      clients=clients,
                                      duration_s=duration_s,
                                      warmup_s=warmup_s)
        cap = max(closed["qps"], 1.0)
        eng.reset_stats()
        open_1x = run_http_open_loop(host, port, feeds, rate_qps=cap,
                                     duration_s=duration_s,
                                     clients=clients)
        eng.reset_stats()
        open_4x = run_http_open_loop(
            host, port, feeds, rate_qps=cap * overload_factor,
            duration_s=duration_s, clients=clients)
        st = eng.stats()
        untyped = (open_4x["statuses"].get("5xx", 0)
                   + open_4x["statuses"].get("transport", 0))
        non200 = sum(v for k, v in open_4x["statuses"].items()
                     if k != "ok")
        # 1×-load reference: the closed loop at capacity IS sustained
        # 1× load (every request sees the full pipeline); the open-1×
        # leg is reported too, but its pacer runs slightly under
        # saturation whenever `behind` > 0, which flatters its p99 —
        # ratio-vs-closed is the stable acceptance number on a 1-core
        # box whose capacity measurement itself swings ±15%
        p99_1x = max(closed["p99_ms"], 1e-9)
        return {
            "scenario": "overload",
            "max_queue_rows": max_queue_rows,
            "deadline_ms": deadline_ms,
            "capacity_qps_1x": round(cap, 1),
            "closed_1x": closed, "open_1x": open_1x,
            "open_overload": open_4x,
            "overload_factor": overload_factor,
            "accepted_p99_ms_1x": closed["p99_ms"],
            "accepted_p99_ms_1x_open": open_1x["p99_ms"],
            "accepted_p99_ms_overload": open_4x["p99_ms"],
            "p99_ratio": round(open_4x["p99_ms"] / p99_1x, 2),
            "p99_ratio_vs_open_1x": round(
                open_4x["p99_ms"] / max(open_1x["p99_ms"], 1e-9), 2),
            "shed_rate_overload": round(
                non200 / max(open_4x["offered"], 1), 4),
            "untyped_failures": untyped,
            "all_refusals_typed": untyped == 0,
            "engine": st,
        }
    finally:
        ing.close()


def run_chaos_scenario(n_rows: int = 64, dim: int = 8,
                       n_feeds: int = 24, ttl_s: float = 0.3,
                       breaker_reset_s: float = 0.8
                       ) -> Dict[str, object]:
    """Pserver-death-mid-HTTP-serving: a raw VarServer serves the
    embedding rows, the engine fronts it with an EmbeddingCache and
    the circuit breaker on. Phase 1 warms the cache over HTTP; phase 2
    kills the server (connection-severing shutdown — the in-process
    SIGKILL equivalent) and expires the TTL, so every predict must
    serve BEYOND-TTL cache rows flagged degraded with zero 5xx; phase
    3 promotes a replacement endpoint via a PR 6 moved ClusterView and
    asserts the path un-degrades by itself. Returns phase counters;
    ``ok`` iff dark-window 5xx == 0 and recovery went fresh."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core, ps_membership
    from paddle_tpu.fluid.ps_rpc import VarClient, VarServer, reset_breakers
    from paddle_tpu.serving import (EmbeddingCache, ServingEngine,
                                    ServingIngress, rewrite_sparse_lookups)

    rng = np.random.RandomState(3)
    table = rng.rand(n_rows, dim).astype(np.float32)

    def serve_table(name, rows, prefetch=False, trainer_id=0):
        return table[np.asarray(rows, np.int64)]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.data("ids", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(ids, size=[n_rows, dim],
                                     param_attr="emb_chaos",
                                     is_distributed=True)
        out = fluid.layers.fc(fluid.layers.reshape(emb, [-1, dim]), 4,
                              act="softmax")
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)

    slot = f"127.0.0.1:{free_port()}"
    ps_prog, _ = rewrite_sparse_lookups(main, [slot],
                                        tables=["emb_chaos"])
    feeds = [{"ids": np.array([[i % n_rows]], np.int64)}
             for i in range(n_feeds)]

    flags_before = {k: core.globals_[k] for k in (
        "FLAGS_rpc_circuit_breaker", "FLAGS_rpc_breaker_failures",
        "FLAGS_rpc_breaker_reset_s", "FLAGS_rpc_retry_times",
        "FLAGS_rpc_deadline")}
    core.globals_["FLAGS_rpc_circuit_breaker"] = True
    core.globals_["FLAGS_rpc_breaker_failures"] = 1
    core.globals_["FLAGS_rpc_breaker_reset_s"] = breaker_reset_s
    core.globals_["FLAGS_rpc_retry_times"] = 0
    core.globals_["FLAGS_rpc_deadline"] = 2000
    ps_membership.reset_views()
    reset_breakers()
    VarClient.reset_pool()

    srv = VarServer(slot, {"prefetch_rows": serve_table}).start()
    cache = EmbeddingCache(ttl_s=ttl_s, max_entries=10000,
                           serve_stale=True)
    eng = ServingEngine(program=ps_prog, scope=scope,
                        feed_names=["ids"], fetch_names=[out],
                        max_batch=8, max_queue_delay_ms=1.0,
                        num_workers=2, embedding_cache=cache)
    ing = ServingIngress({"chaos": eng},
                         default_deadline_ms=3000.0).start()
    cli = HttpClient("127.0.0.1", ing.port)

    def drive(n):
        ok = degraded = err5xx = other = 0
        for i in range(n):
            status, obj = cli.predict(feeds[i % len(feeds)])
            if status == 200:
                ok += 1
                degraded += bool(obj.get("degraded"))
            elif status >= 500:
                err5xx += 1
            else:
                other += 1
        return {"ok": ok, "degraded": degraded, "5xx": err5xx,
                "other": other}

    try:
        warm = drive(n_feeds)           # fills the cache (fresh)
        srv.shutdown()                  # the in-process SIGKILL
        time.sleep(ttl_s + 0.05)        # every cached row beyond TTL
        dark = drive(n_feeds)           # must serve stale, degraded
        dark_stats = eng.stats()

        # PR 6-style promotion: a replacement serves the shard at a
        # NEW physical endpoint; the moved view re-points the slot
        new_ep = f"127.0.0.1:{free_port()}"
        srv2 = VarServer(new_ep, {"prefetch_rows": serve_table}).start()
        ps_membership.install_view(
            ps_membership.ClusterView.initial([slot]).moved(
                slot, new_ep, epoch=1))
        time.sleep(breaker_reset_s + 0.05)  # breaker half-open window
        recovered = drive(n_feeds)
        rec_fresh = drive(n_feeds)      # fully fresh once TTLs renew
        final_stats = eng.stats()
        srv2.shutdown()
        return {
            "scenario": "chaos", "warm": warm, "dark": dark,
            "recovered": recovered, "recovered_fresh": rec_fresh,
            "dark_degraded_responses": dark_stats["degraded"],
            "breaker": final_stats.get("breakers", {}),
            "cache": final_stats.get("embedding_cache", {}),
            "ok": (dark["5xx"] == 0 and dark["degraded"] == dark["ok"]
                   and dark["ok"] == n_feeds
                   and rec_fresh["degraded"] == 0
                   and rec_fresh["ok"] == n_feeds),
        }
    finally:
        cli.close()
        ing.close()
        try:
            srv.shutdown()
        except Exception:
            pass
        for k, v in flags_before.items():
            core.globals_[k] = v
        ps_membership.reset_views()
        reset_breakers()
        VarClient.reset_pool()


# ---------------------------------------------------------------------- CLI
def _build_mlp_engine(max_batch: int, delay_ms: float, workers: int):
    from paddle_tpu.serving import ServingEngine

    main, scope, out_name, feeds = build_mlp_serving_model()
    eng = ServingEngine(program=main, scope=scope, feed_names=["x"],
                        fetch_names=[out_name], max_batch=max_batch,
                        max_queue_delay_ms=delay_ms, num_workers=workers)
    return eng, feeds


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=("closed", "open", "http"),
                    default="closed")
    ap.add_argument("--scenario", choices=("overload", "chaos"),
                    default=None,
                    help="http-mode scripted scenarios (ISSUE 9): "
                         "overload = 1x/4x open-loop shed run, chaos = "
                         "pserver kill mid-serving")
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--rate", type=float, default=500.0,
                    help="open-loop target QPS")
    ap.add_argument("--duration", type=float, default=3.0)
    ap.add_argument("--warmup", type=float, default=0.5)
    ap.add_argument("--max-batch", type=int, default=16)
    ap.add_argument("--delay-ms", type=float, default=2.0)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--deadline-ms", type=float, default=500.0,
                    help="http-mode per-request budget")
    ap.add_argument("--max-queue-rows", type=int, default=None,
                    help="http-mode admission bound (default: "
                         "clients/2 — must sit below the client "
                         "concurrency to engage)")
    ap.add_argument("--naive", action="store_true",
                    help="one-request-one-dispatch lane (max_batch=1)")
    ap.add_argument("--endpoints", default=None,
                    help="http-mode fleet targets, comma-separated "
                         "host:port — round-robin + retry-on-503/"
                         "reset across them instead of building a "
                         "local engine")
    ap.add_argument("--directory", default=None,
                    help="fleet directory endpoint (host:port) — the "
                         "router follows the live membership view; "
                         "combinable with --endpoints as the seed list")
    ap.add_argument("--fleet-loop", choices=("closed", "open"),
                    default="closed",
                    help="fleet-mode load shape (open paces --rate)")
    args = ap.parse_args(argv)

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax
    if not os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", "cpu")

    if args.mode == "http":
        if args.endpoints or args.directory:
            # fleet mode: drive LIVE remote members (the chaos harness
            # and multi-process fleet lanes), no local engine at all
            eps = ([e.strip() for e in args.endpoints.split(",")
                    if e.strip()] if args.endpoints else [])
            rng = np.random.RandomState(0)
            feeds = [{"x": rng.rand(784).astype(np.float32)}
                     for _ in range(64)]
            if args.fleet_loop == "open":
                res = run_http_fleet_open_loop(
                    eps, feeds, rate_qps=args.rate,
                    duration_s=args.duration, clients=args.clients,
                    deadline_ms=args.deadline_ms, model="mlp",
                    directory_ep=args.directory)
            else:
                res = run_http_fleet_closed_loop(
                    eps, feeds, clients=args.clients,
                    duration_s=args.duration, warmup_s=args.warmup,
                    deadline_ms=args.deadline_ms, model="mlp",
                    directory_ep=args.directory)
            print(json.dumps({"mode": "http-fleet",
                              "loop": args.fleet_loop,
                              "result": res}, default=str))
            return 0
        if args.scenario == "overload":
            res = run_overload_scenario(
                clients=args.clients, duration_s=args.duration,
                warmup_s=args.warmup, max_batch=args.max_batch,
                max_queue_rows=args.max_queue_rows,
                deadline_ms=args.deadline_ms, workers=args.workers)
            print(json.dumps({"mode": "http", "result": res},
                             default=str))
            return 0 if res["all_refusals_typed"] else 1
        if args.scenario == "chaos":
            res = run_chaos_scenario()
            print(json.dumps({"mode": "http", "result": res},
                             default=str))
            return 0 if res["ok"] else 1
        # plain closed loop through a live ingress
        from paddle_tpu.serving import AdmissionController, ServingIngress

        eng, feeds = _build_mlp_engine(args.max_batch, args.delay_ms,
                                       args.workers)
        eng._admission = AdmissionController(
            max_queue_rows=(args.max_queue_rows
                            if args.max_queue_rows is not None
                            else max(4, args.clients // 2)))
        ing = ServingIngress({"mlp": eng},
                             default_deadline_ms=args.deadline_ms).start()
        try:
            eng.warm()
            eng.reset_stats()
            res = run_http_closed_loop(
                "127.0.0.1", ing.port, feeds, clients=args.clients,
                duration_s=args.duration, warmup_s=args.warmup)
            print(json.dumps({"mode": "http", "result": res,
                              "ingress": ing.stats()["ingress"],
                              "engine": eng.stats()}, default=str))
        finally:
            ing.close()
        return 0

    max_batch = 1 if args.naive else args.max_batch
    eng, feeds = _build_mlp_engine(max_batch, args.delay_ms, args.workers)
    try:
        eng.warm()
        eng.reset_stats()
        if args.mode == "closed":
            res = run_closed_loop(eng.predict, feeds,
                                  clients=args.clients,
                                  duration_s=args.duration,
                                  warmup_s=args.warmup)
        else:
            res = run_open_loop(eng.submit, feeds, rate_qps=args.rate,
                                duration_s=args.duration)
        st = eng.stats()
        print(json.dumps({"mode": args.mode, "naive": bool(args.naive),
                          "result": res, "engine": st,
                          "overload_counters": {
                              k: st[k] for k in (
                                  "shed", "deadline_expired",
                                  "degraded", "breaker_open")}},
                         default=str))
    finally:
        eng.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
