"""Serving-plane load generator — closed- and open-loop traffic against
a ServingEngine (docs/SERVING.md "Bench methodology").

Library (bench.py + tests/test_serving.py import these):
  * ``run_closed_loop(predict, feeds, clients, duration_s)`` — N client
    threads, each submits its next request the moment the previous one
    completes (throughput-under-concurrency; latency EXCLUDES client
    think time). The shape bench.py's serving lanes measure.
  * ``run_open_loop(submit, feeds, rate_qps, duration_s)`` — one pacing
    thread fires async submits on a fixed-rate schedule regardless of
    completions (latency-under-load; queueing delay INCLUDED — the
    number a p99 SLO is about). Reports ``behind`` when the pacer
    cannot hold the target rate.
  * ``start_inproc_pserver`` / ``push_table`` — the in-process
    listen_and_serv harness the serving PS lanes and tests run against
    (same shape as tests/test_ps_membership.py's protocol harness).

CLI (manual runs)::

    JAX_PLATFORMS=cpu python tools/serving_loadgen.py \
        --clients 16 --duration 3 --max-batch 16 --mode closed
    python tools/serving_loadgen.py --mode open --rate 500 --naive

Prints one JSON line: loadgen results + the engine's stats() surface.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _percentiles(lats_s: Sequence[float]) -> Dict[str, float]:
    from paddle_tpu.serving.engine import percentiles_ms
    return percentiles_ms(lats_s, suffix="_ms")


def run_closed_loop(predict: Callable[[dict], object],
                    feeds: Sequence[dict], clients: int = 16,
                    duration_s: float = 3.0,
                    warmup_s: float = 0.5) -> Dict[str, float]:
    """Closed loop: ``clients`` threads call ``predict(feed)`` back to
    back for ``duration_s`` (after ``warmup_s`` whose samples are
    discarded — first-touch compiles and cold caches must not land in
    the percentiles). Returns qps + latency percentiles over the
    measured window."""
    results: List[List] = [[] for _ in range(clients)]
    errors: List[BaseException] = []
    go = threading.Event()
    t_box = {}

    def worker(wid: int):
        rs = results[wid]
        go.wait()
        end = t_box["t0"] + warmup_s + duration_s
        i = wid
        while time.perf_counter() < end:
            feed = feeds[i % len(feeds)]
            i += clients
            t = time.perf_counter()
            try:
                predict(feed)
            except BaseException as e:  # surface, don't hang the join
                errors.append(e)
                return
            rs.append((time.perf_counter(), t))

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(clients)]
    for t in threads:
        t.start()
    t_box["t0"] = time.perf_counter()
    go.set()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    cut = t_box["t0"] + warmup_s
    done = sorted((td, td - ts) for rs in results for td, ts in rs
                  if ts >= cut)
    if not done:
        return {"qps": 0.0, "n": 0, "clients": clients,
                **_percentiles([])}
    span = done[-1][0] - cut
    out = {"qps": len(done) / span if span > 1e-9 else 0.0,
           "n": len(done), "clients": clients,
           "duration_s": round(span, 3)}
    out.update(_percentiles([lat for _t, lat in done]))
    return out


def run_open_loop(submit: Callable[[dict], object], feeds: Sequence[dict],
                  rate_qps: float, duration_s: float = 3.0,
                  timeout_s: float = 120.0) -> Dict[str, float]:
    """Open loop: submit async requests at ``rate_qps`` for
    ``duration_s``; latency = submit→fulfilment (futures must expose
    ``.wait(timeout)`` and ``.t_submit``/``.t_done`` stamps — the
    serving Request contract). ``behind`` counts schedule slots the
    pacer missed (the engine saturated: achieved rate < target)."""
    if rate_qps <= 0:
        raise ValueError("rate_qps must be > 0")
    period = 1.0 / float(rate_qps)
    futs = []
    behind = 0
    start = time.perf_counter()
    next_t = start
    i = 0
    while True:
        now = time.perf_counter()
        if now >= start + duration_s:
            break
        if now < next_t:
            time.sleep(next_t - now)
        fut = submit(feeds[i % len(feeds)])
        futs.append(fut)
        i += 1
        next_t += period
        if time.perf_counter() > next_t + period:
            behind += 1
    for f in futs:
        f.wait(timeout_s)
    lats = [f.t_done - f.t_submit for f in futs]
    span = (max(f.t_done for f in futs) - start) if futs else 0.0
    out = {"target_qps": float(rate_qps),
           "qps": len(futs) / span if span > 1e-9 else 0.0,
           "n": len(futs), "behind": behind,
           "duration_s": round(span, 3)}
    out.update(_percentiles(lats))
    return out


# ------------------------------------------------------------------ harness
def start_inproc_pserver(endpoint: str, bind: str = "",
                         standby: bool = False,
                         pserver_endpoints: Sequence[str] = (),
                         sync: bool = False):
    """One in-process listen_and_serv loop on its own scope/thread —
    the serving PS lanes' pserver harness. Returns (thread, scope);
    stop with ``stop_inproc_pserver``."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core

    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        main.global_block().append_op(
            type="listen_and_serv", inputs={}, outputs={},
            attrs={"endpoint": endpoint, "sync_mode": sync,
                   "Fanin": 1, "optimize_blocks": [],
                   "grad_to_block_id": [],
                   "pserver_endpoints": list(pserver_endpoints)
                   or [endpoint],
                   "bind_endpoint": bind, "standby": standby,
                   "replica_of": ""})
    scope = core.Scope()
    exe = fluid.Executor()
    th = threading.Thread(
        target=lambda: exe.run(main, scope=scope, feed={},
                               fetch_list=[]), daemon=True)
    th.start()
    return th, scope


def stop_inproc_pserver(physical_ep: str, thread) -> None:
    from paddle_tpu.fluid.ps_rpc import VarClient
    try:
        c = VarClient(physical_ep, connect_timeout=5.0, channels=1,
                      resolve=False)
        c.stop()
        c.close()
    except Exception:
        pass
    thread.join(timeout=10)


def push_table(endpoints: Sequence[str], name: str,
               table: np.ndarray) -> None:
    """Install a full embedding table on every pserver (each serves its
    ``id %% n`` shard out of it; prefetch_rows indexes by GLOBAL id, so
    shipping the whole array keeps the harness trivially bit-equal to
    the local oracle)."""
    from paddle_tpu.fluid.ps_rpc import VarClient
    for ep in endpoints:
        c = VarClient(ep, connect_timeout=30.0, channels=1)
        c.send_var(name, np.asarray(table))
        c.close()


def free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def build_mlp_serving_model(n_feeds: int = 64):
    """The mnist-shaped serving model every mnist lane measures — ONE
    builder so the CLI loadgen and bench.py serve_mnist stay comparable
    by construction. Returns (program, scope, out_name, feeds) with
    params initialized and ``feeds`` a list of single-row feed dicts."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[784], dtype="float32")
        h = fluid.layers.fc(x, 256, act="relu")
        out = fluid.layers.fc(h, 10, act="softmax")
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    rng = np.random.RandomState(0)
    feeds = [{"x": rng.rand(784).astype(np.float32)}
             for _ in range(n_feeds)]
    return main, scope, out.name, feeds


# ---------------------------------------------------------------------- CLI
def _build_mlp_engine(max_batch: int, delay_ms: float, workers: int):
    from paddle_tpu.serving import ServingEngine

    main, scope, out_name, feeds = build_mlp_serving_model()
    eng = ServingEngine(program=main, scope=scope, feed_names=["x"],
                        fetch_names=[out_name], max_batch=max_batch,
                        max_queue_delay_ms=delay_ms, num_workers=workers)
    return eng, feeds


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=("closed", "open"), default="closed")
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--rate", type=float, default=500.0,
                    help="open-loop target QPS")
    ap.add_argument("--duration", type=float, default=3.0)
    ap.add_argument("--warmup", type=float, default=0.5)
    ap.add_argument("--max-batch", type=int, default=16)
    ap.add_argument("--delay-ms", type=float, default=2.0)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--naive", action="store_true",
                    help="one-request-one-dispatch lane (max_batch=1)")
    args = ap.parse_args(argv)

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax
    if not os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", "cpu")

    max_batch = 1 if args.naive else args.max_batch
    eng, feeds = _build_mlp_engine(max_batch, args.delay_ms, args.workers)
    try:
        eng.warm()
        eng.reset_stats()
        if args.mode == "closed":
            res = run_closed_loop(eng.predict, feeds,
                                  clients=args.clients,
                                  duration_s=args.duration,
                                  warmup_s=args.warmup)
        else:
            res = run_open_loop(eng.submit, feeds, rate_qps=args.rate,
                                duration_s=args.duration)
        print(json.dumps({"mode": args.mode, "naive": bool(args.naive),
                          "result": res, "engine": eng.stats()},
                         default=str))
    finally:
        eng.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
