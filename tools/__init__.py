"""Framework tooling (reference: tools/ — timeline, benchmarks, inspectors)."""
