#!/usr/bin/env python
"""Single-op benchmark harness (reference:
paddle/fluid/operators/benchmark/op_tester.cc — standalone binary
benchmarking one op from a config of input shapes/dtypes/attrs).

TPU framing: measures both the eager dispatch and the jitted (XLA-compiled)
kernel, which is what actually runs inside a compiled program step.

Usage:
    python tools/op_bench.py --op softmax --inputs X:64x1024:float32 \
        --attrs axis=-1 --repeat 200
    python tools/op_bench.py --op elementwise_add \
        --inputs X:1024x1024:float32,Y:1024x1024:float32
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np


def parse_inputs(spec: str):
    """"X:64x128:float32,Y:128:int64" -> {slot: (shape, dtype)}"""
    out = {}
    for item in spec.split(","):
        parts = item.split(":")
        slot = parts[0]
        shape = tuple(int(d) for d in parts[1].split("x")) if len(parts) > 1 \
            else (1,)
        dtype = parts[2] if len(parts) > 2 else "float32"
        out[slot] = (shape, dtype)
    return out


def parse_attrs(items):
    attrs = {}
    for item in items or []:
        k, v = item.split("=", 1)
        try:
            attrs[k] = json.loads(v)
        except json.JSONDecodeError:
            attrs[k] = v
    return attrs


def make_array(rng, shape, dtype):
    if np.issubdtype(np.dtype(dtype), np.integer):
        return rng.randint(0, 10, shape).astype(dtype)
    return rng.rand(*shape).astype(dtype)


def bench_op(op_type: str, input_spec, attrs, repeat=100, warmup=10,
             grad=False, seed=0):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.registry import OPS
    import paddle_tpu.ops  # noqa: F401 — registrations
    info = OPS.get(op_type)
    rng = np.random.RandomState(seed)
    ins = {slot: [jnp.asarray(make_array(rng, shape, dtype))]
           for slot, (shape, dtype) in input_spec.items()}
    attrs = dict(attrs)
    if info.needs_rng:
        attrs["_rng"] = jax.random.key(seed)
    if info.stateful:
        raise SystemExit(f"op {op_type} is host-stateful; not benchable "
                         f"standalone")

    def run(xs):
        merged = {s: [x] for s, x in zip(ins.keys(), xs)}
        return info.kernel(merged, attrs)
    flat = [v[0] for v in ins.values()]

    # eager
    for _ in range(warmup):
        jax.block_until_ready(list(run(flat).values())[0])
    t0 = time.perf_counter()
    for _ in range(repeat):
        out = run(flat)
    jax.block_until_ready(list(out.values())[0])
    eager_ms = (time.perf_counter() - t0) / repeat * 1e3

    # jitted
    jitted = jax.jit(lambda *xs: run(list(xs)))
    jax.block_until_ready(list(jitted(*flat).values())[0])
    t0 = time.perf_counter()
    for _ in range(repeat):
        out = jitted(*flat)
    jax.block_until_ready(list(out.values())[0])
    jit_ms = (time.perf_counter() - t0) / repeat * 1e3

    nbytes = sum(np.prod(s) * np.dtype(d).itemsize
                 for s, d in input_spec.values())
    return {"op": op_type, "eager_ms": round(eager_ms, 4),
            "jit_ms": round(jit_ms, 4),
            "approx_gbps": round(nbytes / (jit_ms * 1e-3) / 1e9, 2),
            "repeat": repeat}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--op", required=True)
    p.add_argument("--inputs", required=True,
                   help="slot:shape:dtype[,slot:shape:dtype...] e.g. "
                        "X:64x1024:float32")
    p.add_argument("--attrs", nargs="*", help="k=v (v json-parsed)")
    p.add_argument("--repeat", type=int, default=100)
    p.add_argument("--warmup", type=int, default=10)
    args = p.parse_args()
    res = bench_op(args.op, parse_inputs(args.inputs),
                   parse_attrs(args.attrs), args.repeat, args.warmup)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
