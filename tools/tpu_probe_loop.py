#!/usr/bin/env python
"""Round-long TPU tunnel probe loop.

The axon tunnel to the real chip has transient live windows
(VERDICT r03: probe on a loop for the whole session, log every attempt).
Every ``interval`` seconds this spawns the same bounded-time subprocess
probe bench.py uses (a hung tunnel blocks forever inside jax.devices(),
so the timeout is mandatory), appends one JSON line per attempt to
``tools/probe_history.jsonl``, and EXITS 0 the first time the platform
comes back as a real TPU — the parent shell treats exit as the
"tunnel is live, run the first-contact plan NOW" signal. Exits 3 when
``max_hours`` elapse with no live window (the logged history is then the
evidence the tunnel never opened).
"""
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
HISTORY = os.path.join(HERE, "probe_history.jsonl")
PROBE_CODE = ("import jax; d = jax.devices()[0]; "
              "jax.numpy.ones(4).sum().block_until_ready(); "
              "print('PLATFORM=' + d.platform)")


def probe_once(timeout=80):
    t0 = time.time()
    try:
        out = subprocess.run([sys.executable, "-c", PROBE_CODE],
                             capture_output=True, text=True,
                             timeout=timeout, env=os.environ.copy())
        for line in out.stdout.splitlines():
            if line.startswith("PLATFORM="):
                return line.split("=", 1)[1], round(time.time() - t0, 1)
        return ("error rc=%s %s" % (out.returncode,
                                    out.stderr.strip()[-160:]),
                round(time.time() - t0, 1))
    except subprocess.TimeoutExpired:
        return "timeout", round(time.time() - t0, 1)
    except OSError as e:
        return "oserror %r" % (e,), round(time.time() - t0, 1)


def main():
    interval = int(sys.argv[1]) if len(sys.argv) > 1 else 600
    max_hours = float(sys.argv[2]) if len(sys.argv) > 2 else 11.0
    deadline = time.time() + max_hours * 3600
    n = 0
    while time.time() < deadline:
        n += 1
        result, dt = probe_once()
        row = {"t": time.strftime("%Y-%m-%dT%H:%M:%S"), "attempt": n,
               "result": result, "probe_s": dt}
        with open(HISTORY, "a") as f:
            f.write(json.dumps(row) + "\n")
        print(json.dumps(row), flush=True)
        if result not in ("timeout",) and not result.startswith(
                ("error", "oserror", "cpu")):
            print("TPU LIVE after %d attempts" % n, flush=True)
            return 0
        time.sleep(max(5, interval - dt))
    print("no live window in %.1fh (%d attempts)" % (max_hours, n),
          flush=True)
    return 3


if __name__ == "__main__":
    sys.exit(main())
