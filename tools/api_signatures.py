#!/usr/bin/env python
"""Dump the public API surface with signatures (reference:
tools/print_signatures.py feeding the API-diff checkers). One line per
symbol, sorted, so two dumps diff cleanly across versions:

    python tools/api_signatures.py > /tmp/api.txt
    python tools/api_signatures.py --module paddle_tpu.fluid.layers
"""
from __future__ import annotations

import argparse
import inspect
import sys


DEFAULT_MODULES = [
    "paddle_tpu",
    "paddle_tpu.fluid",
    "paddle_tpu.fluid.layers",
    "paddle_tpu.fluid.optimizer",
    "paddle_tpu.fluid.dygraph",
    "paddle_tpu.fluid.io",
    "paddle_tpu.fluid.nets",
    "paddle_tpu.nn",
    "paddle_tpu.nn.functional",
    "paddle_tpu.tensor",
    "paddle_tpu.dataset",
    "paddle_tpu.reader",
    "paddle_tpu.distribution",
    "paddle_tpu.inference",
]


def _sig(obj):
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"


def dump(module_name, out):
    import importlib
    try:
        mod = importlib.import_module(module_name)
    except Exception as e:  # surface but keep dumping the rest
        print(f"{module_name}  <import failed: {type(e).__name__}>",
              file=out)
        return
    names = getattr(mod, "__all__", None) or [
        n for n in dir(mod) if not n.startswith("_")]
    for name in sorted(set(names)):
        obj = getattr(mod, name, None)
        if obj is None:
            continue
        if inspect.isclass(obj):
            print(f"{module_name}.{name}{_sig(obj.__init__)}  [class]",
                  file=out)
            for m_name, m in sorted(vars(obj).items()):
                if m_name.startswith("_") or not callable(m):
                    continue
                print(f"{module_name}.{name}.{m_name}{_sig(m)}", file=out)
        elif callable(obj):
            print(f"{module_name}.{name}{_sig(obj)}", file=out)
        elif not inspect.ismodule(obj):
            print(f"{module_name}.{name}  [value]", file=out)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--module", action="append", default=None)
    args = p.parse_args()
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax
    jax.config.update("jax_platforms", "cpu")
    for m in (args.module or DEFAULT_MODULES):
        dump(m, sys.stdout)


if __name__ == "__main__":
    main()
