#!/usr/bin/env python
"""Concurrency lint: static lock-order + blocking-call analysis over
paddle_tpu/ (the second half of the static-analysis plane —
docs/ANALYSIS.md; the program-level half is fluid/analysis.py).

The lock-order races this repo has actually shipped — the ps_rpc /
ps_membership / slab_spill inversions found only by chaos loops, the
PR 6/10/12 hardening rounds' blocking-calls-under-locks — are all
visible in the source: a ``with self._lock:`` nested (directly or
through a call) inside another, in the opposite order somewhere else.
This tool walks the AST of every module, builds the lock-acquisition
graph, and reports:

  * ``lock-order-cycle`` — two (or more) locks acquired in both orders
    on some pair of code paths: a potential deadlock. Both acquisition
    stacks are reported.
  * ``lock-self-cycle`` — a non-reentrant ``threading.Lock`` re-acquired
    while already held (directly or through a call chain): a guaranteed
    deadlock when that path runs.
  * ``cv-wait-no-timeout`` — ``Condition.wait()``/``wait_for()`` with no
    timeout: an unbounded block that turns a lost notify into a hang
    (the chaos-loop signature).
  * ``socket-under-lock`` — socket send/recv/accept/connect while
    holding a lock: the wire stalls every thread behind the lock.
  * ``file-io-under-lock`` — file I/O (open/os.replace/os.fsync/...)
    while holding a grad/slab/table-class lock (the PR 12 hardening
    class): disk latency serializes the training data plane.

Lock identity is per *declaration site* — ``mod:Class.attr`` for
``self.attr = threading.Lock()`` and ``mod:NAME`` for module globals;
``threading.Condition(self._lock)`` aliases the condition to its
underlying lock. Distinct instances of one class share an identity
(the standard, slightly conservative lint approximation); vetted
exceptions live in an annotated allowlist (tools/lockcheck_allow.txt,
every entry carries a rationale) and suppressed findings are still
reported as suppressed.

Usage:
    python tools/lockcheck.py [--root paddle_tpu]
                              [--allowlist tools/lockcheck_allow.txt]
                              [--json]
Exit status: 0 clean (allowlisted findings excluded), 1 otherwise.
Runs as a tier-1 test (tests/test_analysis.py).
"""
from __future__ import annotations

import argparse
import ast
import fnmatch
import json
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "cv"}

_SOCKET_METHODS = {"sendall", "recv", "recv_into", "accept", "connect"}

# file-I/O call shapes flagged under data-plane locks
_OS_IO = {"replace", "fsync", "rename", "remove", "fdopen"}

# lock ids matching any of these substrings guard the training data
# plane (grad merge, slab/table rows) — disk I/O under them is the
# PR 12 hardening class
_IO_LOCK_HINTS = ("grad", "slab", "spill", "table", "merge", "staging")


class Finding:
    def __init__(self, rule: str, key: str, message: str,
                 sites: Sequence[Tuple[str, int]]):
        self.rule = rule
        self.key = key
        self.message = message
        self.sites = list(sites)

    @property
    def full_key(self) -> str:
        return f"{self.rule}:{self.key}"

    def format(self) -> str:
        locs = ", ".join(f"{f}:{ln}" for f, ln in self.sites[:6])
        return f"[{self.rule}] {self.key}\n    {self.message}\n    at {locs}"

    def as_dict(self):
        return {"rule": self.rule, "key": self.key,
                "message": self.message, "sites": self.sites}


class _Acq:
    """One lock acquisition site: lock id + where."""

    __slots__ = ("lock", "file", "line", "func")

    def __init__(self, lock: str, file: str, line: int, func: str):
        self.lock = lock
        self.file = file
        self.line = line
        self.func = func


class _ModuleIndex(ast.NodeVisitor):
    """Pass 1 over one module: lock declarations, cv aliases, class and
    function inventory, import aliases."""

    def __init__(self, mod: str, file: str):
        self.mod = mod
        self.file = file
        self.locks: Dict[str, str] = {}        # lock id -> kind
        self.aliases: Dict[str, str] = {}      # cv lock id -> aliased id
        self.class_attrs: Dict[str, Set[str]] = {}   # Class -> lock attrs
        self.bases: Dict[str, List[str]] = {}  # Class -> local base names
        self.functions: Set[str] = set()       # qualified local func names
        self.imports: Dict[str, str] = {}      # local alias -> module name
        self._class: Optional[str] = None
        self._func: List[str] = []

    # ---- structure -----------------------------------------------------
    def visit_ClassDef(self, node):
        prev = self._class
        self._class = node.name
        self.class_attrs.setdefault(node.name, set())
        self.bases[node.name] = [b.id for b in node.bases
                                 if isinstance(b, ast.Name)]
        self.generic_visit(node)
        self._class = prev

    def _visit_func(self, node):
        self._func.append(node.name)
        qual = ".".join(self._func)
        self.functions.add(f"{self._class}.{qual}" if self._class else qual)
        self.generic_visit(node)
        self._func.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Import(self, node):
        for a in node.names:
            self.imports[a.asname or a.name.split(".")[0]] = a.name
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        for a in node.names:
            # best-effort: record "from x import y" so y.fn() can resolve
            base = node.module or ""
            self.imports[a.asname or a.name] = (
                f"{base}.{a.name}" if base else a.name)
        self.generic_visit(node)

    # ---- lock declarations ---------------------------------------------
    @staticmethod
    def _lock_ctor(call) -> Optional[Tuple[str, ast.AST]]:
        """('lock'|'rlock'|'cv', first_arg_or_None) when ``call`` is a
        threading.Lock()/RLock()/Condition(...) constructor."""
        if not isinstance(call, ast.Call):
            return None
        fn = call.func
        name = None
        if isinstance(fn, ast.Attribute):
            name = fn.attr
        elif isinstance(fn, ast.Name):
            name = fn.id
        kind = _LOCK_CTORS.get(name or "")
        if kind is None:
            return None
        arg = call.args[0] if call.args else None
        return kind, arg

    def _target_lock_id(self, target) -> Optional[str]:
        if isinstance(target, ast.Name) and self._func == []:
            return f"{self.mod}:{target.id}"
        if isinstance(target, ast.Name) and self._func:
            return None  # function-local lock: invisible outside
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self" and self._class:
            return f"{self.mod}:{self._class}.{target.attr}"
        return None

    def visit_Assign(self, node):
        ctor = self._lock_ctor(node.value)
        if ctor is not None:
            kind, arg = ctor
            for t in node.targets:
                lid = self._target_lock_id(t)
                if lid is None:
                    continue
                self.locks[lid] = kind
                if isinstance(t, ast.Attribute) and self._class:
                    self.class_attrs[self._class].add(t.attr)
                if kind == "cv" and arg is not None:
                    src = self._target_lock_id(arg)
                    if src is not None:
                        self.aliases[lid] = src
        self.generic_visit(node)


class _FuncWalker(ast.NodeVisitor):
    """Pass 2 over one function: with-lock nesting, calls under locks,
    blocking-call findings."""

    def __init__(self, an: "Analyzer", idx: _ModuleIndex,
                 cls: Optional[str], qual: str):
        self.an = an
        self.idx = idx
        self.cls = cls
        self.qual = qual            # "mod:Class.method" / "mod:func"
        self.held: List[_Acq] = []

    # nested defs are walked as their own functions by the analyzer
    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        pass

    # ---- helpers -------------------------------------------------------
    def _resolve_lock(self, expr) -> Optional[str]:
        lid = None
        if isinstance(expr, ast.Name):
            cand = f"{self.idx.mod}:{expr.id}"
            if cand in self.an.locks:
                lid = cand
        elif isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and self.cls:
            lid = self.an.resolve_self_attr(self.idx, self.cls, expr.attr)
        return self.an.canonical(lid) if lid else None

    def _resolve_callee(self, fn) -> Optional[str]:
        mod = self.idx.mod
        if isinstance(fn, ast.Name):
            if fn.id in self.idx.functions:
                return f"{mod}:{fn.id}"
            return None
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            if isinstance(recv, ast.Name) and recv.id == "self" \
                    and self.cls:
                return self.an.resolve_self_method(self.idx, self.cls,
                                                   fn.attr)
            if isinstance(recv, ast.Name):
                target_mod = self.an.resolve_import(self.idx, recv.id)
                if target_mod and f"{target_mod}:{fn.attr}" \
                        in self.an.func_acquires:
                    return f"{target_mod}:{fn.attr}"
        return None

    def _site(self, node) -> Tuple[str, int]:
        return (self.idx.file, getattr(node, "lineno", 0))

    # ---- with ----------------------------------------------------------
    def _visit_with(self, node):
        pushed = 0
        for item in node.items:
            lid = self._resolve_lock(item.context_expr)
            if lid is None:
                continue
            acq = _Acq(lid, self.idx.file, item.context_expr.lineno
                       if hasattr(item.context_expr, "lineno")
                       else node.lineno, self.qual)
            for held in self.held:
                self.an.add_edge(held, acq, via=None)
            self.an.func_direct[self.qual].append(acq)
            self.held.append(acq)
            pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    # ---- calls ---------------------------------------------------------
    def visit_Call(self, node):
        fn = node.func
        # blocking-call findings -----------------------------------------
        if isinstance(fn, ast.Attribute):
            attr = fn.attr
            if attr in ("wait", "wait_for"):
                self._check_wait(node, fn)
            elif attr in _SOCKET_METHODS and self.held:
                self._flag_socket(node, fn)
            elif attr in _OS_IO and isinstance(fn.value, ast.Name) \
                    and fn.value.id == "os":
                self._check_file_io(node, f"os.{attr}")
        elif isinstance(fn, ast.Name) and fn.id == "open":
            self._check_file_io(node, "open")
        # call-graph recording -------------------------------------------
        callee = self._resolve_callee(fn)
        if callee is not None:
            self.an.func_calls[self.qual].append(
                (callee, tuple(self.held), self._site(node)))
        self.generic_visit(node)

    def _check_wait(self, node, fn):
        recv = fn.value
        is_cv = False
        if isinstance(recv, ast.Attribute) and isinstance(recv.value,
                                                          ast.Name) \
                and recv.value.id == "self" and self.cls:
            lid = self.an.resolve_self_attr(self.idx, self.cls, recv.attr)
            is_cv = lid is not None and self.an.locks.get(lid) == "cv"
        elif isinstance(recv, ast.Name):
            lid = f"{self.idx.mod}:{recv.id}"
            is_cv = self.an.locks.get(lid) == "cv"
        if not is_cv:
            return
        has_timeout = any(kw.arg == "timeout" for kw in node.keywords)
        pos_needed = 1 if fn.attr == "wait" else 2  # wait_for(pred, t)
        if len(node.args) >= pos_needed:
            has_timeout = True
        if not has_timeout:
            f, ln = self._site(node)
            self.an.findings.append(Finding(
                "cv-wait-no-timeout", f"{self.qual}:{fn.attr}",
                f"Condition.{fn.attr}() without a timeout in {self.qual} "
                "— a lost notify (killed peer, exception before "
                "notify_all) hangs this thread forever; every waiter in "
                "this codebase bounds its wait and re-checks liveness",
                [(f, ln)]))

    def _flag_socket(self, node, fn):
        f, ln = self._site(node)
        top = self.held[-1]
        self.an.findings.append(Finding(
            "socket-under-lock",
            f"{top.lock}:{fn.attr}",
            f"socket .{fn.attr}() while holding {top.lock} in "
            f"{self.qual} — the peer's scheduling delay stalls every "
            "thread contending for the lock (bounded only by the socket "
            "timeout, if one is set)",
            [(f, ln)]))

    def _check_file_io(self, node, what):
        for held in self.held:
            low = held.lock.lower()
            if any(h in low for h in _IO_LOCK_HINTS):
                f, ln = self._site(node)
                self.an.findings.append(Finding(
                    "file-io-under-lock",
                    f"{held.lock}:{what}",
                    f"{what}(...) while holding data-plane lock "
                    f"{held.lock} in {self.qual} — disk latency "
                    "serializes the grad/row path behind this lock "
                    "(the PR 12 hardening class)",
                    [(f, ln)]))
                return


class Analyzer:
    def __init__(self):
        self.indexes: Dict[str, _ModuleIndex] = {}
        self.locks: Dict[str, str] = {}
        self.aliases: Dict[str, str] = {}
        # lock graph: (A, B) -> list of evidence sites
        self.edges: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}
        self.func_direct: Dict[str, List[_Acq]] = {}
        self.func_calls: Dict[str, List] = {}
        self.func_acquires: Dict[str, Set[str]] = {}
        self.findings: List[Finding] = []

    # ---- identity ------------------------------------------------------
    def canonical(self, lid: str) -> str:
        seen = set()
        while lid in self.aliases and lid not in seen:
            seen.add(lid)
            lid = self.aliases[lid]
        return lid

    def resolve_self_attr(self, idx: _ModuleIndex, cls: str,
                          attr: str) -> Optional[str]:
        """self.<attr> as a lock id: exact class, then local base
        classes, then — only if UNIQUE — any class in the module (covers
        mixins); ambiguity returns None rather than guessing."""
        cand = f"{idx.mod}:{cls}.{attr}"
        if cand in self.locks:
            return cand
        for base in idx.bases.get(cls, ()):
            got = self.resolve_self_attr(idx, base, attr)
            if got is not None:
                return got
        owners = [c for c, attrs in idx.class_attrs.items() if attr in attrs]
        if len(owners) == 1:
            return f"{idx.mod}:{owners[0]}.{attr}"
        return None

    def resolve_self_method(self, idx: _ModuleIndex, cls: str,
                            meth: str) -> Optional[str]:
        cand = f"{cls}.{meth}"
        if cand in idx.functions:
            return f"{idx.mod}:{cand}"
        for base in idx.bases.get(cls, ()):
            got = self.resolve_self_method(idx, base, meth)
            if got is not None:
                return got
        return None

    def resolve_import(self, idx: _ModuleIndex, alias: str
                       ) -> Optional[str]:
        target = idx.imports.get(alias)
        if target is None:
            return None
        # match the tail of any analyzed module path
        for mod in self.indexes:
            if mod == target or mod.endswith("." + target.split(".")[-1]) \
                    and target.split(".")[-1] == mod.rsplit(".", 1)[-1]:
                return mod
        return None

    def add_edge(self, held: _Acq, acq: _Acq,
                 via: Optional[str]) -> None:
        a, b = held.lock, acq.lock
        evid = (acq.file, acq.line,
                f"{acq.func}" + (f" via {via}" if via else "")
                + f" (outer {held.lock} at {held.file}:{held.line})")
        self.edges.setdefault((a, b), []).append(evid)

    # ---- pipeline ------------------------------------------------------
    def index_files(self, files: Dict[str, str]) -> None:
        for relpath, src in sorted(files.items()):
            mod = relpath[:-3].replace(os.sep, "/").replace("/", ".")
            try:
                tree = ast.parse(src)
            except SyntaxError as e:  # pragma: no cover
                self.findings.append(Finding(
                    "parse-error", relpath, str(e), [(relpath, 0)]))
                continue
            idx = _ModuleIndex(mod, relpath)
            idx.visit(tree)
            idx._tree = tree
            self.indexes[mod] = idx
            self.locks.update(idx.locks)
            self.aliases.update(idx.aliases)

    def walk_functions(self) -> None:
        for mod, idx in self.indexes.items():
            self._walk_module(idx, idx._tree, cls=None, prefix=())

    def _walk_module(self, idx, node, cls, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self._walk_module(idx, child, cls=child.name, prefix=())
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                q = prefix + (child.name,)
                qual = f"{idx.mod}:" + (f"{cls}." if cls else "") \
                    + ".".join(q)
                self.func_direct.setdefault(qual, [])
                self.func_calls.setdefault(qual, [])
                w = _FuncWalker(self, idx, cls, qual)
                for stmt in child.body:
                    w.visit(stmt)
                # nested defs: separate walk (thread bodies live there),
                # same class context
                self._walk_module(idx, child, cls=cls, prefix=q)

    def propagate(self) -> None:
        """Transitive lock sets per function, then call-mediated edges:
        holding L while calling f() that (transitively) acquires M is an
        L->M ordering."""
        acq: Dict[str, Set[str]] = {
            f: {a.lock for a in acquisitions}
            for f, acquisitions in self.func_direct.items()}
        self.func_acquires = acq
        changed = True
        while changed:
            changed = False
            for f, calls in self.func_calls.items():
                for callee, _held, _site in calls:
                    extra = acq.get(callee, set()) - acq.setdefault(f,
                                                                    set())
                    if extra:
                        acq[f] |= extra
                        changed = True
        for f, calls in self.func_calls.items():
            for callee, held, site in calls:
                if not held:
                    continue
                for target in sorted(acq.get(callee, ())):
                    for h in held:
                        fake = _Acq(target, site[0], site[1], callee)
                        self.add_edge(h, fake, via=callee)

    def detect_cycles(self) -> None:
        # self-cycles: non-reentrant Lock re-acquired while held
        for (a, b), evid in sorted(self.edges.items()):
            if a == b and self.locks.get(self.canonical(a)) == "lock":
                self.findings.append(Finding(
                    "lock-self-cycle", a,
                    f"non-reentrant {a} (threading.Lock) acquired while "
                    "already held — guaranteed deadlock when this path "
                    "runs",
                    [(f, ln) for f, ln, _ in evid[:4]]))
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            if a != b:
                graph.setdefault(a, set()).add(b)
                graph.setdefault(b, set())
        for comp in _sccs(graph):
            if len(comp) < 2:
                continue
            cyc = sorted(comp)
            sites: List[Tuple[str, int]] = []
            detail = []
            for (a, b), evid in sorted(self.edges.items()):
                if a in comp and b in comp and a != b:
                    f, ln, ctx = evid[0]
                    sites.append((f, ln))
                    detail.append(f"{a} -> {b} [{ctx}]")
            self.findings.append(Finding(
                "lock-order-cycle", "|".join(cyc),
                "locks acquired in conflicting orders — potential "
                "deadlock; acquisition stacks: " + "; ".join(detail[:6]),
                sites))


def _sccs(graph: Dict[str, Set[str]]) -> List[Set[str]]:
    """Tarjan strongly-connected components (iterative)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    def strongconnect(v0):
        work = [(v0, iter(sorted(graph.get(v0, ()))))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        on.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                low[work[-1][0]] = min(low[work[-1][0]], low[v])
            if low[v] == index[v]:
                comp = set()
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.add(w)
                    if w == v:
                        break
                out.append(comp)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return out


# --------------------------------------------------------------------------
# allowlist
# --------------------------------------------------------------------------
def load_allowlist(path: Optional[str]) -> List[Tuple[str, str]]:
    """Lines: ``<rule-id> <key-glob>  # rationale``. The rationale is
    MANDATORY — an entry without one is itself an error (the point of
    the allowlist is recorded judgment, not silencing)."""
    entries: List[Tuple[str, str]] = []
    if not path or not os.path.exists(path):
        return entries
    with open(path) as f:
        for i, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if "#" not in line:
                raise SystemExit(
                    f"{path}:{i}: allowlist entry without a rationale "
                    f"comment: {line!r}")
            body = line.split("#", 1)[0].strip()
            parts = body.split(None, 1)
            if len(parts) != 2:
                raise SystemExit(
                    f"{path}:{i}: expected '<rule> <key-glob> # why', "
                    f"got {line!r}")
            entries.append((parts[0], parts[1]))
    return entries


def split_findings(findings: Sequence[Finding],
                   allow: Sequence[Tuple[str, str]]
                   ) -> Tuple[List[Finding], List[Finding]]:
    active, suppressed = [], []
    for f in findings:
        if any(f.rule == rule and fnmatch.fnmatch(f.key, pat)
               for rule, pat in allow):
            suppressed.append(f)
        else:
            active.append(f)
    return active, suppressed


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------
def analyze_files(files: Dict[str, str]) -> List[Finding]:
    """Full pipeline over {relpath: source} — the unit-test entry."""
    an = Analyzer()
    an.index_files(files)
    an.walk_functions()
    an.propagate()
    an.detect_cycles()
    return an.findings


def collect_sources(root: str) -> Dict[str, str]:
    files: Dict[str, str] = {}
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            p = os.path.join(dirpath, fn)
            with open(p, encoding="utf-8") as f:
                files[os.path.relpath(p, os.path.dirname(root))] = f.read()
    return files


def run(root: str, allow_path: Optional[str] = None
        ) -> Tuple[List[Finding], List[Finding]]:
    findings = analyze_files(collect_sources(root))
    return split_findings(findings, load_allowlist(allow_path))


def main(argv=None) -> int:
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(here)
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--root", default=os.path.join(repo, "paddle_tpu"))
    ap.add_argument("--allowlist",
                    default=os.path.join(here, "lockcheck_allow.txt"))
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    active, suppressed = run(args.root, args.allowlist)
    if args.json:
        print(json.dumps({
            "findings": [f.as_dict() for f in active],
            "suppressed": [f.as_dict() for f in suppressed]}, indent=2))
    else:
        for f in active:
            print(f.format())
        print(f"{len(active)} finding(s), {len(suppressed)} allowlisted")
    return 1 if active else 0


if __name__ == "__main__":
    raise SystemExit(main())
