#!/usr/bin/env python
"""One-command TPU first-contact plan (VERDICT r03 item 1).

Runs the whole measurement sequence the moment a tunnel window opens,
prioritized so a SHORT window still banks the headline number first:

  1. flash_gate  — ONE flash config compile+parity (~1 min): validates
                   the current kernel layout lowers under Mosaic before
                   anything depends on it
  2. bert        — bench.py bert (headline samples/s + MFU; cold
                   compile, seeds the .xla_cache executable cache)
  3. bert_warm   — bench.py bert AGAIN in a fresh process: banks the
                   executable-cache-reload proof (xla_cache_entries_
                   before > 0, compile_s collapsed) for the fluid
                   entrypoint, plus a second timing sample
  4. bert_b512   — bench.py bert at PADDLE_TPU_BENCH_BATCH=512: the
                   upward MFU probe (bigger batch = better MXU
                   utilization if it fits; the OOM ladder walks back
                   down if it doesn't)
  5. mfu_bert    — tools/mfu_report.py bert (XLA cost-analysis MFU)
  6. flash_sweep — bench.py flash (resumable block sweep; banks rows)
  7. resnet      — bench.py resnet
  8. longctx     — bench.py longctx (flash causal S=8192 bf16 fwd+bwd —
                   the single-chip long-context lane)
  9. mnist       — bench.py mnist (host-overhead trend row)

Every stage runs in a SUBPROCESS with its own timeout (a hung tunnel
cannot take the plan down) and its one-line JSON result is appended to
tools/first_contact_log.jsonl as it lands — a window that closes
mid-plan keeps everything banked so far. Stages run in order regardless
of earlier failures (a flash-gate failure skips only the sweep).

Usage:  python tools/first_contact.py [--stages bert,mfu_bert,...]
"""
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
LOG = os.path.join(HERE, "first_contact_log.jsonl")

GATE_CODE = """
import json, sys
sys.path.insert(0, {repo!r})
from tools import flash_smoke
row = flash_smoke.run_config(512, 128, 128)
print("ROW=" + json.dumps(row))
"""


def bank(stage, payload):
    rec = {"t": time.strftime("%Y-%m-%dT%H:%M:%S"), "stage": stage,
           **payload}
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)
    return rec


def run_stage(stage, argv, timeout, parse_prefix=None, extra_env=None):
    t0 = time.time()
    env = os.environ.copy()
    if extra_env:
        env.update(extra_env)
    try:
        out = subprocess.run(argv, capture_output=True, text=True,
                             timeout=timeout, cwd=REPO, env=env)
    except subprocess.TimeoutExpired:
        return bank(stage, {"ok": False, "error": f"timeout {timeout}s",
                            "wall_s": round(time.time() - t0, 1)})
    line = None
    for ln in reversed(out.stdout.strip().splitlines() or []):
        if parse_prefix and ln.startswith(parse_prefix):
            line = ln[len(parse_prefix):]
            break
        if not parse_prefix and ln.startswith("{"):
            line = ln
            break
    if out.returncode != 0 or line is None:
        return bank(stage, {"ok": False, "rc": out.returncode,
                            "stderr_tail": out.stderr.strip()[-400:],
                            "wall_s": round(time.time() - t0, 1)})
    try:
        payload = json.loads(line)
    except ValueError:
        payload = {"raw": line[:400]}
    # bench.py's contract prints a JSON line and exits 0 even on errors —
    # an `error` payload is a FAILED stage, not a banked number
    errored = isinstance(payload, dict) and (
        payload.get("unit") == "error" or "error" in payload)
    return bank(stage, {"ok": not errored,
                        "wall_s": round(time.time() - t0, 1),
                        "result": payload})


def probe_alive(timeout=90):
    """One bounded tunnel probe (bench.py's probe shape) — a dead tunnel
    must cost ~80 s, not the first stage's full timeout."""
    code = ("import jax; d = jax.devices()[0]; "
            "jax.numpy.ones(4).sum().block_until_ready(); "
            "print('PLATFORM=' + d.platform)")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout, env=os.environ.copy())
        return any(ln.startswith("PLATFORM=") and "cpu" not in ln
                   for ln in out.stdout.splitlines())
    except (subprocess.TimeoutExpired, OSError):
        return False


def main():
    stages = ["flash_gate", "bert", "bert_warm", "bert_b512", "mfu_bert",
              "flash_sweep", "resnet", "longctx", "mnist"]
    argv = sys.argv[1:]
    for i, a in enumerate(argv):
        if a == "--stages" and i + 1 < len(argv):
            stages = argv[i + 1].split(",")
        elif a.startswith("--stages="):
            stages = a.split("=", 1)[1].split(",")
    if os.environ.get("FIRST_CONTACT_SKIP_PROBE") != "1" and \
            not probe_alive():
        bank("probe", {"ok": False,
                       "error": "tunnel dead at launch (80s probe); "
                                "set FIRST_CONTACT_SKIP_PROBE=1 to force"})
        return 3
    py = sys.executable
    results = {}
    consecutive_timeouts = 0
    for s in stages:
        if consecutive_timeouts >= 2:
            bank(s, {"ok": False,
                     "error": "skipped: 2 consecutive stage timeouts "
                              "(tunnel window closed)"})
            continue
        if s == "flash_gate":
            results[s] = run_stage(
                s, [py, "-c", GATE_CODE.format(repo=REPO)], 600,
                parse_prefix="ROW=")
        elif s in ("bert", "bert_warm", "bert_b512"):
            if s != "bert":
                cold = results.get("bert")
                if cold is not None and not cold.get("ok"):
                    # nothing seeded the cache; a rerun/bigger batch
                    # would fail identically and burn window time
                    bank(s, {"ok": False, "error": "skipped: bert failed"})
                    continue
                if s == "bert_b512" and cold is not None and \
                        (cold.get("result") or {}).get("cpu_smoke"):
                    # tunnel died mid-window and bert fell back to the
                    # CPU smoke config — a batch-512 CPU row is noise
                    bank(s, {"ok": False,
                             "error": "skipped: bert ran cpu_smoke"})
                    continue
            env = {"PADDLE_TPU_BENCH_BATCH": "512"} \
                if s == "bert_b512" else None
            results[s] = run_stage(s, [py, "bench.py", "bert"], 1800,
                                   extra_env=env)
        elif s == "mfu_bert":
            results[s] = run_stage(s, [py, "-m", "tools.mfu_report",
                                       "bert"], 1800)
        elif s == "flash_sweep":
            gate = results.get("flash_gate")
            if gate is not None and not gate.get("ok"):
                bank(s, {"ok": False, "error": "skipped: flash_gate failed"})
                continue
            results[s] = run_stage(s, [py, "bench.py", "flash"], 2400)
        elif s == "resnet":
            results[s] = run_stage(s, [py, "bench.py", "resnet"], 1800)
        elif s == "longctx":
            results[s] = run_stage(s, [py, "bench.py", "longctx"], 900)
        elif s == "mnist":
            results[s] = run_stage(s, [py, "bench.py", "mnist"], 900)
        else:
            bank(s, {"ok": False, "error": "unknown stage"})
            continue
        r = results.get(s)
        if r is not None and not r.get("ok") \
                and "timeout" in str(r.get("error", "")):
            consecutive_timeouts += 1
        elif r is not None and r.get("ok"):
            consecutive_timeouts = 0
    ok = sum(1 for r in results.values() if r.get("ok"))
    print(f"first_contact: {ok}/{len(results)} stages ok — log {LOG}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
