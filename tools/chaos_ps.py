"""Scripted PS-membership chaos driver — drain / kill / rejoin
(docs/FAULT_TOLERANCE.md "Elastic membership").

Drives a real multiprocess sync PS cluster through membership faults and
checks the training outcome against a no-fault oracle:

  * ``drain_rejoin`` — live-drain pserver slot 0 to a warm standby
    mid-training, later drain it BACK (rejoin-in-place: the drained
    source is the destination of the reverse handoff). Trainers never
    restart; per-step losses must be bit-identical to the oracle.
  * ``failover`` — SIGKILL slot 0's primary mid-training with
    FLAGS_ps_replicas=2 and a warm replica attached; trainers stall at
    most ~2x the heartbeat timeout, then finish against the promoted
    replica, bit-identical to the oracle.
  * ``full`` — drain+rejoin on slot 0 AND a SIGKILL failover on slot 1,
    one run (the ISSUE 6 acceptance scenario).

Models: ``linear`` (tests/dist_ps_workload.py — tiny, fast) and
``wide_deep`` (the CTR model from paddle_tpu.models.wide_deep with
distributed embeddings, served by this module's ``worker`` subcommand).

CLI:
  python tools/chaos_ps.py --scenario full --model wide_deep \
      --trainers 3 --steps 12 --hb 2.0

Exit code 0 iff the faulted run finished AND matched the oracle
bit-for-bit. The ``chaos`` pytest marker's slow acceptance test calls
``run_scenario`` directly.
"""
import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # the driver's own admin RPCs import paddle_tpu
    sys.path.insert(0, REPO)
LINEAR_WORKLOAD = os.path.join(REPO, "tests", "dist_ps_workload.py")


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _spawn(args, log_path, env_extra=None):
    env = dict(os.environ,
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""),
               JAX_PLATFORMS="cpu")
    env.update(env_extra or {})
    log = open(log_path, "wb+")
    proc = subprocess.Popen([sys.executable] + list(args), env=env,
                            stdout=log, stderr=log)

    def tail(n=3000):
        log.flush()
        log.seek(0)
        return log.read().decode(errors="replace")[-n:]

    return proc, tail


def _wait_file(path, timeout, procs=(), desc="file"):
    end = time.time() + timeout
    while time.time() < end:
        if os.path.exists(path):
            return
        for p, tail in procs:
            if p.poll() is not None:
                raise RuntimeError(
                    f"process died waiting for {desc}: {tail()}")
        time.sleep(0.1)
    raise TimeoutError(f"{desc} not ready within {timeout}s")


def _progress(path):
    try:
        with open(path) as f:
            return sum(1 for ln in f if ln.strip())
    except OSError:
        return 0


def admin_drain(owner_ep, dest_ep, timeout=120.0):
    """Drain the shard served at ``owner_ep`` (the slot's CURRENT
    primary) into the standby at ``dest_ep``. Returns the handoff
    summary dict from the source."""
    from paddle_tpu.fluid.ps_rpc import VarClient
    cli = VarClient(owner_ep, connect_timeout=min(10.0, timeout),
                    channels=1, resolve=False)
    try:
        return cli.call("drain", dest=dest_ep, _rpc_timeout=timeout)
    finally:
        cli.close()


def server_stats(ep):
    from paddle_tpu.fluid.ps_rpc import VarClient
    cli = VarClient(ep, connect_timeout=5.0, channels=1, resolve=False)
    try:
        return cli.call("stats", _rpc_timeout=10.0)
    finally:
        cli.close()


class Cluster:
    """One sync PS cluster run: n pservers (+ optional standbys and
    replicas for chosen slots), t trainers logging per-step losses."""

    def __init__(self, workdir, model="linear", trainers=2, n_pservers=2,
                 steps=20, hb=2.0, step_sleep=0.15, standby_slots=(),
                 replica_slots=(), sparse_dim=200, batch=32, tag="run",
                 env_extra=None, worker_extra=()):
        self.workdir = workdir
        self.model = model
        self.trainers = trainers
        self.steps = steps
        self.tag = tag
        os.makedirs(workdir, exist_ok=True)
        self.slot_eps = [f"127.0.0.1:{free_port()}"
                         for _ in range(n_pservers)]
        self.standby_eps = {i: f"127.0.0.1:{free_port()}"
                            for i in standby_slots}
        self.replica_eps = {i: f"127.0.0.1:{free_port()}"
                            for i in replica_slots}
        self.env = {"PADDLE_PS_HEARTBEAT_TIMEOUT": str(hb)}
        self.env.update(env_extra or {})
        self.worker_extra = tuple(worker_extra)
        if self.replica_eps:
            self.env["FLAGS_ps_replicas"] = "2"
            self.env["PADDLE_PS_REPLICA_MAP"] = ",".join(
                f"{self.slot_eps[i]}={ep}"
                for i, ep in self.replica_eps.items())
        self.step_sleep = step_sleep
        self.sparse_dim = sparse_dim
        self.batch = batch
        self.procs = []   # (name, proc, tail)
        self.pserver_procs = {}  # slot idx -> (proc, tail)

    # ------------------------------------------------------------ workers
    def _worker_args(self, role, idx, outfile, extra=()):
        eps = ",".join(self.slot_eps)
        if self.model == "linear":
            # model flags go to EVERY role: pservers transpile the same
            # program to host the sparse table shards
            base = [LINEAR_WORKLOAD, role, eps, str(idx),
                    str(self.trainers), str(self.steps), outfile,
                    "--sparse", f"--sparse-dim={self.sparse_dim}"]
            if role == "trainer":
                base += ["--progress", "--no-stop",
                         f"--step-sleep={self.step_sleep}"]
        else:
            base = [os.path.abspath(__file__), "worker", role, eps,
                    str(idx), str(self.trainers), str(self.steps),
                    outfile, f"--sparse-dim={self.sparse_dim}",
                    f"--batch={self.batch}",
                    f"--step-sleep={self.step_sleep}"]
        return base + list(self.worker_extra) + list(extra)

    def _out(self, name):
        return os.path.join(self.workdir, f"{self.tag}-{name}")

    def start_servers(self, timeout=120.0):
        waits = []
        for i, ep in enumerate(self.slot_eps):
            ready = self._out(f"ps{i}.ready")
            p, tail = _spawn(self._worker_args("pserver", i, ready),
                             self._out(f"ps{i}.log"),
                             dict(self.env,
                                  PADDLE_TPU_TRACE_ROLE=f"pserver{i}"))
            self.procs.append((f"ps{i}", p, tail))
            self.pserver_procs[i] = (p, tail)
            waits.append((ready, p, tail))
        for i, bind in self.standby_eps.items():
            ready = self._out(f"standby{i}.ready")
            p, tail = _spawn(
                self._worker_args("standby", i, ready,
                                  extra=[f"--bind={bind}"]),
                self._out(f"standby{i}.log"), self.env)
            self.procs.append((f"standby{i}", p, tail))
            waits.append((ready, p, tail))
        for i, bind in self.replica_eps.items():
            ready = self._out(f"replica{i}.ready")
            p, tail = _spawn(
                self._worker_args("standby", i, ready,
                                  extra=[f"--bind={bind}", "--replica"]),
                self._out(f"replica{i}.log"), self.env)
            self.procs.append((f"replica{i}", p, tail))
            waits.append((ready, p, tail))
        for ready, p, tail in waits:
            _wait_file(ready, timeout, [(p, tail)], desc=ready)

    def start_trainers(self):
        self.trainer_outs = []
        for t in range(self.trainers):
            out = self._out(f"t{t}.json")
            p, tail = _spawn(self._worker_args("trainer", t, out),
                             self._out(f"t{t}.log"),
                             dict(self.env,
                                  PADDLE_TPU_TRACE_ROLE=f"trainer{t}"))
            self.procs.append((f"t{t}", p, tail))
            self.trainer_outs.append((out, p, tail))

    def trainer_progress(self, t=0):
        return _progress(self.trainer_outs[t][0] + ".progress")

    def wait_progress(self, n, t=0, timeout=300.0):
        end = time.time() + timeout
        while time.time() < end:
            if self.trainer_progress(t) >= n:
                return
            p, tail = self.trainer_outs[t][1:]
            if p.poll() is not None:
                raise RuntimeError(
                    f"trainer {t} died at progress "
                    f"{self.trainer_progress(t)}: {tail()}")
            time.sleep(0.05)
        raise TimeoutError(
            f"trainer {t} stuck at {self.trainer_progress(t)}/{n}")

    def kill_pserver(self, slot):
        p, _tail = self.pserver_procs[slot]
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=30)

    def join_trainers(self, timeout=600.0):
        losses = []
        for out, p, tail in self.trainer_outs:
            rc = p.wait(timeout=timeout)
            if rc != 0:
                raise RuntimeError(f"trainer exited rc={rc}: {tail()}")
            data = json.load(open(out))
            losses.append(data if isinstance(data, list)
                          else data.get("losses"))
        return losses

    def shutdown(self):
        for _name, p, _tail in self.procs:
            if p.poll() is None:
                p.kill()
        for _name, p, _tail in self.procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------
def run_scenario(scenario, workdir, model="linear", trainers=3,
                 n_pservers=2, steps=14, hb=2.0, drain_at=3, rejoin_at=7,
                 kill_at=5, step_sleep=0.15, sparse_dim=200, batch=32,
                 with_oracle=True):
    """Run one chaos scenario (+ a no-fault oracle) and compare
    per-trainer per-step losses bit-for-bit. Returns a result dict."""
    result = {"scenario": scenario, "model": model, "events": []}
    common = dict(model=model, trainers=trainers, n_pservers=n_pservers,
                  steps=steps, hb=hb, step_sleep=step_sleep,
                  sparse_dim=sparse_dim, batch=batch)
    if with_oracle:
        oracle = Cluster(workdir, tag="oracle", **common)
        try:
            oracle.start_servers()
            oracle.start_trainers()
            result["oracle_losses"] = oracle.join_trainers()
        finally:
            oracle.shutdown()

    standby_slots = (0,) if scenario in ("drain_rejoin", "full") else ()
    replica_slots = () if scenario == "drain_rejoin" else \
        ((1,) if scenario == "full" and n_pservers > 1 else (0,))
    run = Cluster(workdir, tag="chaos", standby_slots=standby_slots,
                  replica_slots=replica_slots, **common)
    try:
        run.start_servers()
        run.start_trainers()
        stall_bound = 3 * hb + 10
        if scenario in ("drain_rejoin", "full"):
            slot = run.slot_eps[0]
            standby = run.standby_eps[0]
            run.wait_progress(drain_at)
            summary = admin_drain(slot, standby)
            result["events"].append(("drain", slot, standby, summary))
            run.wait_progress(rejoin_at, timeout=stall_bound + 120)
            summary = admin_drain(standby, slot)  # rejoin-in-place
            result["events"].append(("rejoin", standby, slot, summary))
        if scenario in ("failover", "full"):
            kslot = 1 if scenario == "full" and n_pservers > 1 else 0
            base = max(drain_at, rejoin_at) if scenario == "full" \
                else 0
            run.wait_progress(base + kill_at, timeout=stall_bound + 180)
            t_kill = time.time()
            run.kill_pserver(kslot)
            result["events"].append(
                ("sigkill", run.slot_eps[kslot], None, None))
            # trainers must get moving again within ~2x hb (+slack)
            target = run.trainer_progress(0) + 2
            run.wait_progress(min(target, steps),
                              timeout=stall_bound + 60)
            result["failover_stall_s"] = time.time() - t_kill
        result["losses"] = run.join_trainers(timeout=600.0)
    finally:
        run.shutdown()
    if with_oracle:
        result["bit_identical"] = \
            result["losses"] == result["oracle_losses"]
    return result


# ---------------------------------------------------------------------------
# wide_deep worker subcommand (pserver / standby / trainer roles)
# ---------------------------------------------------------------------------
def _flag_value(name, default=None):
    for a in sys.argv:
        if a.startswith(name + "="):
            return a.split("=", 1)[1]
    return default


def run_worker():
    role, eps, idx, trainers, steps, outfile = sys.argv[2:8]
    idx, trainers, steps = int(idx), int(trainers), int(steps)
    sparse_dim = int(_flag_value("--sparse-dim", 200) or 200)
    batch = int(_flag_value("--batch", 32) or 32)
    step_sleep = float(_flag_value("--step-sleep", 0) or 0)

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid.transpiler import DistributeTranspiler
    from paddle_tpu.models import wide_deep

    def build():
        return wide_deep.build_wide_deep_program(
            num_dense=4, num_slots=3, sparse_dim=sparse_dim,
            embedding_dim=4, hidden=(16, 16), lr=1e-2, with_auc=False,
            is_distributed=True, optimizer=fluid.optimizer.SGD(1e-2))

    main, startup, feeds, loss, _auc = build()
    from paddle_tpu.fluid.transpiler import DistributeTranspilerConfig
    cfg = DistributeTranspilerConfig()
    if "--async-overlap" in sys.argv:
        # ps_round comm tail (docs/PS_DATA_PLANE.md "Async overlap");
        # FLAGS_async_staleness rides the env into this subprocess
        cfg.async_overlap = True
    t = DistributeTranspiler(cfg)
    with fluid.program_guard(main, startup):
        t.transpile(trainer_id=idx if role == "trainer" else 0,
                    pservers=eps, trainers=trainers, sync_mode=True,
                    program=main, startup_program=startup)
    exe = fluid.Executor()
    scope = core.Scope()
    if role in ("pserver", "standby"):
        ep = eps.split(",")[idx]
        if role == "standby":
            bind = _flag_value("--bind")
            pprog = t.get_pserver_program(
                ep, bind_endpoint=bind, standby=True,
                replica_of=ep if "--replica" in sys.argv else "")
        else:
            pprog = t.get_pserver_program(ep)
        pstart = t.get_startup_program(ep, pprog)
        with fluid.scope_guard(scope):
            exe.run(pstart)
            open(outfile, "w").write("ready")
            exe.run(pprog)
        return

    from paddle_tpu.fluid.ps_rpc import VarClient, WorkerHeartBeat
    hb_interval = max(0.25, float(
        os.environ.get("PADDLE_PS_HEARTBEAT_TIMEOUT", 60.0)) / 4)
    beat = WorkerHeartBeat(eps.split(","), idx,
                           interval=hb_interval).start()
    nb = wide_deep.ctr_reader(batch, num_dense=4, num_slots=3,
                              sparse_dim=sparse_dim, seed=idx)
    losses = []
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            prog = t.get_trainer_program()
            for s in range(steps):
                (lv,) = exe.run(prog, feed=nb(), fetch_list=[loss])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
                with open(outfile + ".progress", "a") as pf:
                    pf.write(f"{s} {losses[-1]!r}\n")
                if step_sleep:
                    time.sleep(step_sleep)
            # flush the async-overlap staleness pipe before the
            # pservers are released (no-op in plain sync mode)
            from paddle_tpu.fluid.communicator import drain_async_rounds
            drain_async_rounds()
    finally:
        beat.stop()
    json.dump(losses, open(outfile, "w"))


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "worker":
        run_worker()
        return 0
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scenario", default="full",
                    choices=["drain_rejoin", "failover", "full"])
    ap.add_argument("--model", default="linear",
                    choices=["linear", "wide_deep"])
    ap.add_argument("--trainers", type=int, default=3)
    ap.add_argument("--pservers", type=int, default=2)
    ap.add_argument("--steps", type=int, default=14)
    ap.add_argument("--hb", type=float, default=2.0)
    ap.add_argument("--drain-at", type=int, default=3)
    ap.add_argument("--rejoin-at", type=int, default=7)
    ap.add_argument("--kill-at", type=int, default=5)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--no-oracle", action="store_true")
    ap.add_argument("--trace-dir", default=None,
                    help="stream FLAGS_trace_dir shards from every "
                         "chaos process and run a tools/timeline.py "
                         "merge smoke over them afterwards "
                         "(docs/OBSERVABILITY.md)")
    args = ap.parse_args()
    workdir = args.workdir or os.path.join(
        tempfile.gettempdir(), f"chaos_ps_{int(time.time())}")
    if args.trace_dir:
        # subprocesses inherit the env; the chaos trainers/pservers
        # each stream a shard the merge smoke below combines
        os.makedirs(args.trace_dir, exist_ok=True)
        os.environ["FLAGS_trace_dir"] = args.trace_dir
    res = run_scenario(args.scenario, workdir, model=args.model,
                       trainers=args.trainers, n_pservers=args.pservers,
                       steps=args.steps, hb=args.hb,
                       drain_at=args.drain_at, rejoin_at=args.rejoin_at,
                       kill_at=args.kill_at,
                       with_oracle=not args.no_oracle)
    print(json.dumps(
        {k: v for k, v in res.items() if "losses" not in k}, indent=1,
        default=str))
    if args.trace_dir:
        # timeline-merge smoke: the shards the run just streamed must
        # combine into one clock-corrected timeline (exit non-zero on
        # an empty/unmergeable dir — the chaos driver doubles as the
        # obs plane's multiprocess canary)
        from tools import timeline as _timeline
        summary = _timeline.merge_shards(
            args.trace_dir,
            out=os.path.join(args.trace_dir, "timeline.json"))
        print("trace merge:", json.dumps(summary, indent=1))
        if summary["n_events"] == 0:
            print("trace merge produced ZERO events — shards empty?")
            return 1
    if res.get("oracle_losses") is not None:
        print("bit_identical:", res["bit_identical"])
        if not res["bit_identical"]:
            for t, (a, b) in enumerate(zip(res["losses"],
                                           res["oracle_losses"])):
                if a != b:
                    print(f"trainer {t} diverged: chaos={a[-3:]} "
                          f"oracle={b[-3:]}")
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
