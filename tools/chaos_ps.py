"""Scripted PS-membership chaos driver — drain / kill / rejoin
(docs/FAULT_TOLERANCE.md "Elastic membership").

Drives a real multiprocess sync PS cluster through membership faults and
checks the training outcome against a no-fault oracle:

  * ``drain_rejoin`` — live-drain pserver slot 0 to a warm standby
    mid-training, later drain it BACK (rejoin-in-place: the drained
    source is the destination of the reverse handoff). Trainers never
    restart; per-step losses must be bit-identical to the oracle.
  * ``failover`` — SIGKILL slot 0's primary mid-training with
    FLAGS_ps_replicas=2 and a warm replica attached; trainers stall at
    most ~2x the heartbeat timeout, then finish against the promoted
    replica, bit-identical to the oracle.
  * ``full`` — drain+rejoin on slot 0 AND a SIGKILL failover on slot 1,
    one run (the ISSUE 6 acceptance scenario).
  * ``serving_fleet`` — the self-healing SERVING fleet run (ISSUE 18,
    docs/SERVING.md "Fleet"): N engine subprocesses behind a
    FleetDirectory under open-loop fleet-routed load; a trainer table
    push must become visible in remote responses within a measured
    window, a rolling restart plus one SIGKILL must lose zero accepted
    requests with zero 5xx, and the autopilot must heal the fleet.
  * ``streaming`` — the streaming online-learning lane (ISSUE 20,
    docs/FAULT_TOLERANCE.md "Streaming online learning"): one cluster
    trains a zipfian click stream fully async (``sync_mode=False``
    Communicator, StreamLoader front end, per-step checkpoints) while
    a serving member answers authed HTTP over the SAME tables through
    the invalidation wire. Mid-run: a pserver SIGKILL (replica
    failover) and the shrink cron firing. Pass iff serving answered
    throughout with zero typed-error leaks, the async loss tail lands
    in the sync oracle's neighborhood, and event→served freshness p99
    is bounded and recorded.

Models: ``linear`` (tests/dist_ps_workload.py — tiny, fast) and
``wide_deep`` (the CTR model from paddle_tpu.models.wide_deep with
distributed embeddings, served by this module's ``worker`` subcommand).

CLI:
  python tools/chaos_ps.py --scenario full --model wide_deep \
      --trainers 3 --steps 12 --hb 2.0

Exit code 0 iff the faulted run finished AND matched the oracle
bit-for-bit. The ``chaos`` pytest marker's slow acceptance test calls
``run_scenario`` directly.
"""
import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # the driver's own admin RPCs import paddle_tpu
    sys.path.insert(0, REPO)
LINEAR_WORKLOAD = os.path.join(REPO, "tests", "dist_ps_workload.py")


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _spawn(args, log_path, env_extra=None):
    env = dict(os.environ,
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""),
               JAX_PLATFORMS="cpu")
    env.update(env_extra or {})
    log = open(log_path, "wb+")
    proc = subprocess.Popen([sys.executable] + list(args), env=env,
                            stdout=log, stderr=log)

    def tail(n=3000):
        log.flush()
        log.seek(0)
        return log.read().decode(errors="replace")[-n:]

    return proc, tail


def _wait_file(path, timeout, procs=(), desc="file"):
    end = time.time() + timeout
    while time.time() < end:
        if os.path.exists(path):
            return
        for p, tail in procs:
            if p.poll() is not None:
                raise RuntimeError(
                    f"process died waiting for {desc}: {tail()}")
        time.sleep(0.1)
    raise TimeoutError(f"{desc} not ready within {timeout}s")


def _progress(path):
    try:
        with open(path) as f:
            return sum(1 for ln in f if ln.strip())
    except OSError:
        return 0


def admin_drain(owner_ep, dest_ep, timeout=120.0):
    """Drain the shard served at ``owner_ep`` (the slot's CURRENT
    primary) into the standby at ``dest_ep``. Returns the handoff
    summary dict from the source."""
    from paddle_tpu.fluid.ps_rpc import VarClient
    cli = VarClient(owner_ep, connect_timeout=min(10.0, timeout),
                    channels=1, resolve=False)
    try:
        return cli.call("drain", dest=dest_ep, _rpc_timeout=timeout)
    finally:
        cli.close()


def server_stats(ep):
    from paddle_tpu.fluid.ps_rpc import VarClient
    cli = VarClient(ep, connect_timeout=5.0, channels=1, resolve=False)
    try:
        return cli.call("stats", _rpc_timeout=10.0)
    finally:
        cli.close()


class Cluster:
    """One sync PS cluster run: n pservers (+ optional standbys and
    replicas for chosen slots), t trainers logging per-step losses."""

    def __init__(self, workdir, model="linear", trainers=2, n_pservers=2,
                 steps=20, hb=2.0, step_sleep=0.15, standby_slots=(),
                 replica_slots=(), sparse_dim=200, batch=32, tag="run",
                 env_extra=None, worker_extra=()):
        self.workdir = workdir
        self.model = model
        self.trainers = trainers
        self.steps = steps
        self.tag = tag
        os.makedirs(workdir, exist_ok=True)
        self.slot_eps = [f"127.0.0.1:{free_port()}"
                         for _ in range(n_pservers)]
        self.standby_eps = {i: f"127.0.0.1:{free_port()}"
                            for i in standby_slots}
        self.replica_eps = {i: f"127.0.0.1:{free_port()}"
                            for i in replica_slots}
        self.env = {"PADDLE_PS_HEARTBEAT_TIMEOUT": str(hb)}
        self.env.update(env_extra or {})
        self.worker_extra = tuple(worker_extra)
        if self.replica_eps:
            self.env["FLAGS_ps_replicas"] = "2"
            self.env["PADDLE_PS_REPLICA_MAP"] = ",".join(
                f"{self.slot_eps[i]}={ep}"
                for i, ep in self.replica_eps.items())
        self.step_sleep = step_sleep
        self.sparse_dim = sparse_dim
        self.batch = batch
        self.procs = []   # (name, proc, tail)
        self.pserver_procs = {}  # slot idx -> (proc, tail)

    # ------------------------------------------------------------ workers
    def _worker_args(self, role, idx, outfile, extra=()):
        eps = ",".join(self.slot_eps)
        if self.model == "linear":
            # model flags go to EVERY role: pservers transpile the same
            # program to host the sparse table shards
            base = [LINEAR_WORKLOAD, role, eps, str(idx),
                    str(self.trainers), str(self.steps), outfile,
                    "--sparse", f"--sparse-dim={self.sparse_dim}"]
            if role == "trainer":
                base += ["--progress", "--no-stop",
                         f"--step-sleep={self.step_sleep}"]
        else:
            base = [os.path.abspath(__file__), "worker", role, eps,
                    str(idx), str(self.trainers), str(self.steps),
                    outfile, f"--sparse-dim={self.sparse_dim}",
                    f"--batch={self.batch}",
                    f"--step-sleep={self.step_sleep}"]
        return base + list(self.worker_extra) + list(extra)

    def _out(self, name):
        return os.path.join(self.workdir, f"{self.tag}-{name}")

    def start_servers(self, timeout=120.0):
        waits = []
        for i, ep in enumerate(self.slot_eps):
            ready = self._out(f"ps{i}.ready")
            p, tail = _spawn(self._worker_args("pserver", i, ready),
                             self._out(f"ps{i}.log"),
                             dict(self.env,
                                  PADDLE_TPU_TRACE_ROLE=f"pserver{i}"))
            self.procs.append((f"ps{i}", p, tail))
            self.pserver_procs[i] = (p, tail)
            waits.append((ready, p, tail))
        for i, bind in self.standby_eps.items():
            ready = self._out(f"standby{i}.ready")
            p, tail = _spawn(
                self._worker_args("standby", i, ready,
                                  extra=[f"--bind={bind}"]),
                self._out(f"standby{i}.log"), self.env)
            self.procs.append((f"standby{i}", p, tail))
            waits.append((ready, p, tail))
        for i, bind in self.replica_eps.items():
            ready = self._out(f"replica{i}.ready")
            p, tail = _spawn(
                self._worker_args("standby", i, ready,
                                  extra=[f"--bind={bind}", "--replica"]),
                self._out(f"replica{i}.log"), self.env)
            self.procs.append((f"replica{i}", p, tail))
            waits.append((ready, p, tail))
        for ready, p, tail in waits:
            _wait_file(ready, timeout, [(p, tail)], desc=ready)

    def start_trainers(self):
        self.trainer_outs = []
        for t in range(self.trainers):
            out = self._out(f"t{t}.json")
            p, tail = _spawn(self._worker_args("trainer", t, out),
                             self._out(f"t{t}.log"),
                             dict(self.env,
                                  PADDLE_TPU_TRACE_ROLE=f"trainer{t}"))
            self.procs.append((f"t{t}", p, tail))
            self.trainer_outs.append((out, p, tail))

    def trainer_progress(self, t=0):
        return _progress(self.trainer_outs[t][0] + ".progress")

    def wait_progress(self, n, t=0, timeout=300.0):
        end = time.time() + timeout
        while time.time() < end:
            if self.trainer_progress(t) >= n:
                return
            p, tail = self.trainer_outs[t][1:]
            if p.poll() is not None:
                raise RuntimeError(
                    f"trainer {t} died at progress "
                    f"{self.trainer_progress(t)}: {tail()}")
            time.sleep(0.05)
        raise TimeoutError(
            f"trainer {t} stuck at {self.trainer_progress(t)}/{n}")

    def kill_pserver(self, slot):
        p, _tail = self.pserver_procs[slot]
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=30)

    def join_trainers(self, timeout=600.0):
        losses = []
        for out, p, tail in self.trainer_outs:
            rc = p.wait(timeout=timeout)
            if rc != 0:
                raise RuntimeError(f"trainer exited rc={rc}: {tail()}")
            data = json.load(open(out))
            losses.append(data if isinstance(data, list)
                          else data.get("losses"))
        return losses

    def shutdown(self):
        for _name, p, _tail in self.procs:
            if p.poll() is None:
                p.kill()
        for _name, p, _tail in self.procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------
def run_scenario(scenario, workdir, model="linear", trainers=3,
                 n_pservers=2, steps=14, hb=2.0, drain_at=3, rejoin_at=7,
                 kill_at=5, step_sleep=0.15, sparse_dim=200, batch=32,
                 with_oracle=True):
    """Run one chaos scenario (+ a no-fault oracle) and compare
    per-trainer per-step losses bit-for-bit. Returns a result dict."""
    result = {"scenario": scenario, "model": model, "events": []}
    common = dict(model=model, trainers=trainers, n_pservers=n_pservers,
                  steps=steps, hb=hb, step_sleep=step_sleep,
                  sparse_dim=sparse_dim, batch=batch)
    if with_oracle:
        oracle = Cluster(workdir, tag="oracle", **common)
        try:
            oracle.start_servers()
            oracle.start_trainers()
            result["oracle_losses"] = oracle.join_trainers()
        finally:
            oracle.shutdown()

    standby_slots = (0,) if scenario in ("drain_rejoin", "full") else ()
    replica_slots = () if scenario == "drain_rejoin" else \
        ((1,) if scenario == "full" and n_pservers > 1 else (0,))
    run = Cluster(workdir, tag="chaos", standby_slots=standby_slots,
                  replica_slots=replica_slots, **common)
    try:
        run.start_servers()
        run.start_trainers()
        stall_bound = 3 * hb + 10
        if scenario in ("drain_rejoin", "full"):
            slot = run.slot_eps[0]
            standby = run.standby_eps[0]
            run.wait_progress(drain_at)
            summary = admin_drain(slot, standby)
            result["events"].append(("drain", slot, standby, summary))
            run.wait_progress(rejoin_at, timeout=stall_bound + 120)
            summary = admin_drain(standby, slot)  # rejoin-in-place
            result["events"].append(("rejoin", standby, slot, summary))
        if scenario in ("failover", "full"):
            kslot = 1 if scenario == "full" and n_pservers > 1 else 0
            base = max(drain_at, rejoin_at) if scenario == "full" \
                else 0
            run.wait_progress(base + kill_at, timeout=stall_bound + 180)
            t_kill = time.time()
            run.kill_pserver(kslot)
            result["events"].append(
                ("sigkill", run.slot_eps[kslot], None, None))
            # trainers must get moving again within ~2x hb (+slack)
            target = run.trainer_progress(0) + 2
            run.wait_progress(min(target, steps),
                              timeout=stall_bound + 60)
            result["failover_stall_s"] = time.time() - t_kill
        result["losses"] = run.join_trainers(timeout=600.0)
    finally:
        run.shutdown()
    if with_oracle:
        result["bit_identical"] = \
            result["losses"] == result["oracle_losses"]
    return result


# ---------------------------------------------------------------------------
# serving-fleet scenario (ISSUE 18): rolling restart + SIGKILL under load
# ---------------------------------------------------------------------------
def _scrape_metric_stat(host, port, name):
    """Pull one histogram's (_sum, _count) off a member's /metrics
    exposition — the registry-scraped freshness-window evidence."""
    import http.client as _http
    conn = _http.HTTPConnection(host, int(port), timeout=5.0)
    try:
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode("utf-8", "replace")
    finally:
        conn.close()
    s = c = None
    for ln in text.splitlines():
        if ln.startswith(name + "_sum"):
            s = float(ln.rsplit(None, 1)[1])
        elif ln.startswith(name + "_count"):
            c = float(ln.rsplit(None, 1)[1])
    return s, c


def run_serving_fleet_scenario(workdir, members=3, n_rows=64, dim=8,
                               hb=1.0, rate_qps=60.0, duration_s=75.0,
                               clients=8):
    """The self-healing-fleet acceptance run (docs/SERVING.md "Fleet"):

    the driver hosts the embedding table (a raw VarServer), the
    trainer-side ``InvalidationPublisher``, the ``FleetDirectory`` and
    an ``Autopilot``; ``members`` serving engines run as REAL
    subprocesses (``serving-member`` subcommand). Under open-loop
    fleet-routed load it then injects, in order:

      1. a trainer table push + invalidation broadcast — every member
         must reflect the new rows in its HTTP responses within a
         bounded, MEASURED window (wall-clock here, plus the members'
         registry-scraped staleness histograms);
      2. a rolling restart — each original member SIGTERMed (directory
         drain → ingress drain → exit) and replaced, zero lost
         accepted requests;
      3. one SIGKILL — heartbeat eviction within ~2×hb, the autopilot
         heals the fleet back to ``members``.

    ``ok`` iff the load saw ZERO 5xx / fleet-dark errors, every
    response is accounted (accepted or typed-shed), freshness was
    in-bounds on every member, and the fleet healed.
    """
    import threading

    import numpy as np

    os.makedirs(workdir, exist_ok=True)
    from paddle_tpu.fluid.ps_rpc import VarServer
    from paddle_tpu.serving import (Autopilot, FleetDirectory,
                                    InvalidationPublisher, SLO)
    from paddle_tpu.serving.fleet import scrape_http_member
    from tools.serving_loadgen import HttpClient, run_http_fleet_open_loop

    result = {"scenario": "serving_fleet", "members": members,
              "events": []}
    rng = np.random.RandomState(7)
    table = rng.rand(n_rows, dim).astype(np.float32)
    tlock = threading.Lock()

    def serve_table(name, rows, prefetch=False, trainer_id=0):
        with tlock:
            return table[np.asarray(rows, np.int64)].copy()

    table_ep = f"127.0.0.1:{free_port()}"
    pub_ep = f"127.0.0.1:{free_port()}"
    dir_ep = f"127.0.0.1:{free_port()}"
    srv = VarServer(table_ep, {"prefetch_rows": serve_table}).start()
    pub = InvalidationPublisher(pub_ep).start()
    directory = FleetDirectory(dir_ep, heartbeat_timeout_s=hb).start()

    member_procs = {}       # name -> (proc, tail, ready_path)
    next_idx = [0]
    spawn_lock = threading.Lock()

    def spawn_member():
        with spawn_lock:
            i = next_idx[0]
            next_idx[0] += 1
        name = f"m{i}"
        ready = os.path.join(workdir, f"{name}.ready")
        p, tail = _spawn(
            [os.path.abspath(__file__), "serving-member", name,
             table_ep, pub_ep, dir_ep, ready,
             f"--rows={n_rows}", f"--dim={dim}", f"--hb={hb}"],
            os.path.join(workdir, f"{name}.log"))
        member_procs[name] = (p, tail, ready)
        return name

    def wait_member(name, timeout=120.0):
        p, tail, ready = member_procs[name]
        _wait_file(ready, timeout, [(p, tail)], desc=f"member {name}")
        return int(open(ready).read().strip())

    def wait_view(n, timeout=60.0, desc=""):
        end = time.time() + timeout
        while time.time() < end:
            if len(directory.view().endpoints()) == n:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"fleet view stuck at {len(directory.view().endpoints())} "
            f"members, want {n} {desc}")

    def scrape_all():
        out = []
        for ep in directory.view().endpoints():
            host, port = ep.rsplit(":", 1)
            try:
                out.append(scrape_http_member(ep))
            except Exception:
                out.append(None)
        return out

    autopilot = None
    load_box = {}
    try:
        ports = {}
        for _ in range(members):
            name = spawn_member()
            ports[name] = wait_member(name)
        wait_view(members, desc="at start")

        feeds = [{"ids": np.array([[i % n_rows]], np.int64)}
                 for i in range(32)]

        def load():
            load_box["res"] = run_http_fleet_open_loop(
                [], feeds, rate_qps=rate_qps, duration_s=duration_s,
                clients=clients, model="fleet", directory_ep=dir_ep)
        load_th = threading.Thread(target=load, daemon=True)
        load_th.start()
        time.sleep(1.0)  # let the loop establish against the fleet

        # ---- 1. trainer push: update rows, broadcast, measure until
        # every member's HTTP response reflects the new values
        push_ids = list(range(8))
        with tlock:
            table[push_ids] += 1.0
            expect = [float(table[i].sum()) for i in push_ids]
        t_push = time.time()
        pub.publish("emb_fleet", push_ids)
        fresh_by_member = {}
        deadline = t_push + 10.0
        pending = dict(ports)
        while pending and time.time() < deadline:
            for name, port in list(pending.items()):
                cli = HttpClient("127.0.0.1", port)
                try:
                    status, obj = cli.predict(
                        {"ids": [[push_ids[0]]]}, model="fleet")
                finally:
                    cli.close()
                if status == 200:
                    got = float(np.asarray(obj["outputs"][0])
                                .reshape(-1)[0])
                    if abs(got - expect[0]) < 1e-3:
                        fresh_by_member[name] = time.time() - t_push
                        del pending[name]
            if pending:
                time.sleep(0.02)
        result["freshness_s"] = {k: round(v, 4)
                                 for k, v in fresh_by_member.items()}
        result["events"].append(("push", push_ids, None, None))
        fresh_ok = len(fresh_by_member) == members
        result["freshness_window_s"] = (
            round(max(fresh_by_member.values()), 4)
            if fresh_by_member else None)

        # ---- 2. rolling restart of every ORIGINAL member — surge
        # style: the replacement JOINS before the old member drains,
        # so the routable fleet never dips below target strength
        for name in list(ports):
            repl = spawn_member()
            wait_member(repl)
            wait_view(members + 1, desc=f"surge {repl} for {name}")
            p, tail, _ready = member_procs[name]
            p.send_signal(signal.SIGTERM)
            rc = p.wait(timeout=120)
            result["events"].append(("sigterm", name, rc, None))
            wait_view(members, desc=f"after rolling {name}->{repl}")

        # ---- 3. SIGKILL one member; eviction + autopilot heal. The
        # autopilot arms only now: its min_members healing must not
        # race the DELIBERATE drains of phase 2 (a real deployment
        # coordinates restarts with the controller the same way)
        slo = SLO(p99_ms=5000.0, max_shed_rate=1.0,
                  max_queue_rows=1 << 20, min_members=members,
                  max_members=members + 2)
        autopilot = Autopilot(
            scrape_all, slo,
            spawn_fn=spawn_member,
            drain_fn=lambda: None,  # scale-down is not this scenario
            interval_s=0.5, cooldown_s=2.0).start()
        victim = next(n for n, (p, _t, _r) in member_procs.items()
                      if p.poll() is None)
        vp = member_procs[victim][0]
        t_kill = time.time()
        vp.send_signal(signal.SIGKILL)
        vp.wait(timeout=30)
        wait_view(members - 1, timeout=2 * hb + 20,
                  desc="eviction after SIGKILL")
        result["evict_s"] = round(time.time() - t_kill, 3)
        result["events"].append(("sigkill", victim, None, None))
        wait_view(members, timeout=120, desc="autopilot heal")
        result["heal_s"] = round(time.time() - t_kill, 3)

        load_th.join(timeout=duration_s + 120)
        res = load_box.get("res") or {}
        result["load"] = res

        # registry-scraped staleness evidence off one live member
        for ep in directory.view().endpoints():
            host, port = ep.rsplit(":", 1)
            try:
                s, c = _scrape_metric_stat(
                    host, port, "serving_cache_staleness_window_seconds")
            except Exception:
                continue
            if c:
                result["staleness_hist"] = {
                    "count": c, "mean_s": round(s / c, 6)}
                break

        statuses = dict(res.get("statuses") or {})
        bad = {k: v for k, v in statuses.items()
               if k not in ("ok", "429", "504")}
        accounted = (sum(statuses.values()) == res.get("offered", -1))
        result["checks"] = {
            "zero_5xx_or_dark": not bad,
            "all_requests_accounted": accounted,
            "freshness_all_members": fresh_ok,
            "evicted_within_2xhb": result["evict_s"] <= 2 * hb + 10,
            "healed": True,
        }
        result["ok"] = all(result["checks"].values())
        return result
    finally:
        if autopilot is not None:
            autopilot.stop()
        for name, (p, tail, _r) in member_procs.items():
            if p.poll() is None:
                p.kill()
        for name, (p, _t, _r) in member_procs.items():
            try:
                p.wait(timeout=10)
            except Exception:
                pass
        directory.close()
        pub.close()
        srv.shutdown()


def run_serving_member():
    """``serving-member`` subcommand: one fleet engine process — MLP-
    free value-reflective model (``out = sum(emb[id])``, so a table
    push is directly observable in the HTTP response), EmbeddingCache
    + InvalidationSubscriber, ingress, FleetMember. SIGTERM runs the
    zero-lost drain (directory first, then ingress) and exits 0."""
    name, table_ep, pub_ep, dir_ep, ready_file = sys.argv[2:7]
    n_rows = int(_flag_value("--rows", 64) or 64)
    dim = int(_flag_value("--dim", 8) or 8)
    hb = float(_flag_value("--hb", 1.0) or 1.0)
    ttl_s = float(_flag_value("--ttl", 30.0) or 30.0)

    import threading

    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core
    from paddle_tpu.serving import (EmbeddingCache, FleetMember,
                                    InvalidationSubscriber, ServingEngine,
                                    ServingIngress, rewrite_sparse_lookups)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.data("ids", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(ids, size=[n_rows, dim],
                                     param_attr="emb_fleet",
                                     is_distributed=True)
        out = fluid.layers.reduce_sum(
            fluid.layers.reshape(emb, [-1, dim]), dim=1)
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    ps_prog, _ = rewrite_sparse_lookups(main, [table_ep],
                                        tables=["emb_fleet"])
    cache = EmbeddingCache(ttl_s=ttl_s, max_entries=100000,
                           serve_stale=True)
    eng = ServingEngine(program=ps_prog, scope=scope, feed_names=["ids"],
                        fetch_names=[out], max_batch=8,
                        max_queue_delay_ms=1.0, num_workers=2,
                        embedding_cache=cache)
    ing = ServingIngress({"fleet": eng}).start()
    sub = InvalidationSubscriber(pub_ep, cache, name=name,
                                 poll_wait_s=0.5).start()
    member = FleetMember(name, dir_ep, f"127.0.0.1:{ing.port}",
                         ingress=ing, beat_interval_s=max(0.1, hb / 4))
    member.start()

    done = threading.Event()

    def on_term(_sig, _frm):
        # drain OFF the signal thread: member.drain() does wire RPCs +
        # the ingress inflight wait — too much for a handler frame
        threading.Thread(target=lambda: (member.drain(), done.set()),
                         daemon=True).start()

    signal.signal(signal.SIGTERM, on_term)
    open(ready_file, "w").write(str(ing.port))
    done.wait()
    sub.stop()
    ing.close()
    eng.close()


# ---------------------------------------------------------------------------
# streaming scenario (ISSUE 20): async train + serve one cluster, survive
# a pserver SIGKILL and a shrink-cron firing under authed HTTP load
# ---------------------------------------------------------------------------
def click_stream(offset, n_rows=64, seed=7):
    """Seekable synthetic zipfian click stream: event #i is derived
    from a counter-keyed RandomState, so ``click_stream(k)`` replays
    event k bit-identically no matter where a previous reader stopped —
    the StreamLoader seek contract. Yields ``(x, ids, y)`` samples:
    4 dense features, one zipf-hot clicked id, and a linear label with
    a per-id bias (learnable, so loss trends down)."""
    import numpy as np
    w_true = np.array([1.0, -2.0, 0.5, 3.0], np.float32)
    i = int(offset)
    while True:
        rs = np.random.RandomState((seed * 1000003 + i) % (2**31 - 1))
        rid = min(n_rows - 1, int(rs.zipf(1.5)) - 1)
        x = rs.rand(4).astype(np.float32)
        bias = np.random.RandomState(seed ^ (rid + 1)).uniform(-1.0, 1.0)
        y = np.array([float(x @ w_true) * 0.1 + bias], np.float32)
        yield (x, np.array([rid], np.int64), y)
        i += 1


def build_stream_model(n_rows=64, dim=8, lr=0.05):
    """The streaming CTR-ish model: dense features + one distributed
    embedding (``emb_stream``), trained with SGD. Returns
    ``(main, startup, feed_vars, loss)``."""
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[4], dtype="float32")
        ids = fluid.data("ids", shape=[1], dtype="int64")
        y = fluid.data("y", shape=[1], dtype="float32")
        emb = fluid.layers.embedding(
            ids, size=[n_rows, dim], is_distributed=True,
            param_attr=fluid.ParamAttr(name="emb_stream"))
        emb = fluid.layers.reshape(emb, [-1, dim])
        feat = fluid.layers.concat([x, emb], axis=1)
        pred = fluid.layers.fc(feat, 1,
                               param_attr=fluid.ParamAttr(name="w"),
                               bias_attr=fluid.ParamAttr(name="b"))
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(lr).minimize(loss)
    return main, startup, [x, ids, y], loss


def run_stream_worker():
    """``stream-worker`` subcommand — pserver / standby / trainer roles
    of the streaming cluster. Default mode is fully async
    (``sync_mode=False``: per-var Communicator merge queues, recv
    double buffer); ``--sync`` builds the SYNC oracle cluster the
    driver compares the loss tail against. The async trainer also:

      * feeds from a StreamLoader over ``click_stream`` (resumable
        event offsets, per-step auto-checkpoints under ``--ckpt-dir``
        riding the PR 3 MANIFEST);
      * hosts the InvalidationPublisher at ``--pub-ep`` so the serving
        member's cache tracks its pushes;
      * leaves the shrink cron to ``FLAGS_ps_shrink_every_steps`` in
        the environment (ticked at the async recv step boundary).
    """
    role, eps, idx, trainers, steps, outfile = sys.argv[2:8]
    idx, trainers, steps = int(idx), int(trainers), int(steps)
    n_rows = int(_flag_value("--rows", 64) or 64)
    dim = int(_flag_value("--dim", 8) or 8)
    batch = int(_flag_value("--batch", 8) or 8)
    seed = int(_flag_value("--seed", 7) or 7)
    step_sleep = float(_flag_value("--step-sleep", 0) or 0)
    sync = "--sync" in sys.argv
    pub_ep = _flag_value("--pub-ep")
    ckpt_dir = _flag_value("--ckpt-dir")
    resume = "--resume" in sys.argv

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid.transpiler import DistributeTranspiler

    main, startup, feeds, loss = build_stream_model(n_rows, dim)
    t = DistributeTranspiler()
    with fluid.program_guard(main, startup):
        t.transpile(trainer_id=idx if role == "trainer" else 0,
                    pservers=eps, trainers=trainers, sync_mode=sync,
                    program=main, startup_program=startup)
    exe = fluid.Executor()
    scope = core.Scope()
    if role in ("pserver", "standby"):
        ep = eps.split(",")[idx]
        if role == "standby":
            bind = _flag_value("--bind")
            pprog = t.get_pserver_program(
                ep, bind_endpoint=bind, standby=True,
                replica_of=ep if "--replica" in sys.argv else "")
        else:
            pprog = t.get_pserver_program(ep)
        pstart = t.get_startup_program(ep, pprog)
        with fluid.scope_guard(scope):
            exe.run(pstart)
            open(outfile, "w").write("ready")
            exe.run(pprog)
        return

    # ------------------------------------------------------- trainer role
    comm = pub = None
    if not sync:
        from paddle_tpu.fluid.communicator import Communicator
        comm = Communicator()
        comm.start()
    if pub_ep:
        from paddle_tpu.fluid import ps_rpc
        from paddle_tpu.serving import InvalidationPublisher
        pub = InvalidationPublisher(pub_ep).start()
        ps_rpc.install_invalidation_publisher(pub)

    x, ids, y = feeds
    loader = fluid.DataLoader.from_stream(feed_list=[x, ids, y],
                                          batch_size=batch)
    loader.set_event_source(
        lambda off: click_stream(off, n_rows=n_rows, seed=seed))
    losses = []
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            prog = t.get_trainer_program()
            if ckpt_dir:
                if resume:
                    exe.resume_from(ckpt_dir, program=prog, scope=scope,
                                    dataloader=loader)
                exe.set_auto_checkpoint(ckpt_dir, every_n_steps=1,
                                        program=prog, scope=scope,
                                        dataloader=loader)
            open(outfile + ".up", "w").write("up")
            t_loop = time.time()
            for step, feed in enumerate(loader):
                if step >= steps:
                    break
                (lv,) = exe.run(prog, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
                with open(outfile + ".progress", "a") as pf:
                    pf.write(f"{step} {losses[-1]!r} "
                             f"{loader.stream_offset}\n")
                if step_sleep:
                    time.sleep(step_sleep)
    finally:
        if comm is not None:
            comm.stop()   # drains merge queues in submit order
        if pub is not None:
            pub.close()
    # wall of the training loop INCLUDING the async plane's stop-drain
    # (the sync leg pays its barriers inline; excluding the drain would
    # flatter async) and any step_sleep pacing — bench.py stream_ctr
    # records steps*step_sleep alongside so the pacing is attributable
    json.dump({"losses": losses, "offset": loader.stream_offset,
               "train_wall_s": round(time.time() - t_loop, 4)},
              open(outfile, "w"))


def run_stream_server():
    """``stream-server`` subcommand — the serving member of the
    streaming cluster: value-reflective model (``out = sum(emb[id])``)
    whose lookups are rewritten against the TRAINING pservers
    (``rewrite_sparse_lookups`` — same ``id % n_pservers`` shards), an
    EmbeddingCache kept fresh by the trainer's invalidation wire, and
    an authed HTTP ingress (FLAGS_serving_auth_token from the env).
    Replica failover rides PADDLE_PS_REPLICA_MAP, also from the env."""
    name, eps_csv, pub_ep, ready_file = sys.argv[2:6]
    n_rows = int(_flag_value("--rows", 64) or 64)
    dim = int(_flag_value("--dim", 8) or 8)
    ttl_s = float(_flag_value("--ttl", 30.0) or 30.0)

    import threading

    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core
    from paddle_tpu.serving import (EmbeddingCache, InvalidationSubscriber,
                                    ServingEngine, ServingIngress,
                                    rewrite_sparse_lookups)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.data("ids", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(
            ids, size=[n_rows, dim], is_distributed=True,
            param_attr=fluid.ParamAttr(name="emb_stream"))
        out = fluid.layers.reduce_sum(
            fluid.layers.reshape(emb, [-1, dim]), dim=1)
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    ps_prog, _ = rewrite_sparse_lookups(main, eps_csv.split(","),
                                        tables=["emb_stream"])
    cache = EmbeddingCache(ttl_s=ttl_s, max_entries=100000,
                           serve_stale=True)
    eng = ServingEngine(program=ps_prog, scope=scope, feed_names=["ids"],
                        fetch_names=[out], max_batch=8,
                        max_queue_delay_ms=1.0, num_workers=2,
                        embedding_cache=cache)
    ing = ServingIngress({"stream": eng}).start()
    sub = InvalidationSubscriber(pub_ep, cache, name=name,
                                 poll_wait_s=0.5).start()

    done = threading.Event()

    def on_term(_sig, _frm):
        threading.Thread(target=done.set, daemon=True).start()

    signal.signal(signal.SIGTERM, on_term)
    open(ready_file, "w").write(str(ing.port))
    done.wait()
    sub.stop()
    ing.close()
    eng.close()


def _scrape_histogram_quantile(host, port, name, q=0.99):
    """Bucket-resolution quantile off a /metrics exposition: the
    smallest bucket upper bound covering fraction ``q`` of the
    samples. Returns ``(upper_bound_s_or_None, count)``."""
    import http.client as _http
    conn = _http.HTTPConnection(host, int(port), timeout=5.0)
    try:
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode("utf-8", "replace")
    finally:
        conn.close()
    buckets, total = [], 0.0
    for ln in text.splitlines():
        if ln.startswith(name + "_bucket"):
            le = ln.split('le="', 1)[1].split('"', 1)[0]
            buckets.append((float(le), float(ln.rsplit(None, 1)[1])))
        elif ln.startswith(name + "_count"):
            total = float(ln.rsplit(None, 1)[1])
    if not total:
        return None, 0
    buckets.sort()
    for le, cum in buckets:
        if cum >= q * total:
            return le, int(total)
    return float("inf"), int(total)


def _dig(obj, key):
    """First value for ``key`` anywhere in a nested dict/list."""
    if isinstance(obj, dict):
        if key in obj:
            return obj[key]
        for v in obj.values():
            got = _dig(v, key)
            if got is not None:
                return got
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            got = _dig(v, key)
            if got is not None:
                return got
    return None


def run_streaming_scenario(workdir, n_rows=64, dim=8, batch=8, steps=40,
                           hb=2.0, kill_at=15, shrink_every=10,
                           step_sleep=0.12, clients=3, auth_token="s3cret",
                           with_oracle=True):
    """The ISSUE 20 acceptance lane. Sequence:

      1. SYNC oracle: 2 pservers + 1 sync trainer over the same click
         stream — the loss-neighborhood reference.
      2. Chaos cluster: 2 pservers (slot 1 carries a warm replica),
         1 fully-async streaming trainer (Communicator, per-step
         checkpoints, invalidation publisher, shrink cron), 1 authed
         serving member over the SAME table shards.
      3. Closed-loop authed HTTP load for the whole run; one
         deliberately unauthed probe must bounce with a typed 401.
      4. At trainer step ``kill_at``: SIGKILL pserver slot 1's primary
         — the replica promotes; trainer AND serving re-route.

    Checks: trainer exits 0; every load response is ok or a typed
    refusal (zero 5xx/transport-dark); accepted p99 under the serving
    bar; async loss tail within the sync oracle's neighborhood; shrink
    ran on a surviving pserver; event→served freshness p99 bounded and
    recorded off the member's /metrics histogram."""
    import threading

    import numpy as np

    os.makedirs(workdir, exist_ok=True)
    from paddle_tpu.serving.engine import percentiles_ms
    from tools.serving_loadgen import HttpClient

    result = {"scenario": "streaming", "steps": steps, "events": []}
    me = os.path.abspath(__file__)

    # ---- 1. sync oracle ------------------------------------------------
    oracle_losses = None
    if with_oracle:
        eps = [f"127.0.0.1:{free_port()}" for _ in range(2)]
        eps_csv = ",".join(eps)
        procs = []
        try:
            waits = []
            for i in range(2):
                ready = os.path.join(workdir, f"oracle-ps{i}.ready")
                p, tail = _spawn(
                    [me, "stream-worker", "pserver", eps_csv, str(i),
                     "1", str(steps), ready, "--sync",
                     f"--rows={n_rows}", f"--dim={dim}"],
                    os.path.join(workdir, f"oracle-ps{i}.log"))
                procs.append((p, tail))
                waits.append((ready, p, tail))
            for ready, p, tail in waits:
                _wait_file(ready, 120, [(p, tail)], desc=ready)
            out = os.path.join(workdir, "oracle-t0.json")
            p, tail = _spawn(
                [me, "stream-worker", "trainer", eps_csv, "0", "1",
                 str(steps), out, "--sync", f"--rows={n_rows}",
                 f"--dim={dim}", f"--batch={batch}"],
                os.path.join(workdir, "oracle-t0.log"))
            rc = p.wait(timeout=600)
            if rc != 0:
                raise RuntimeError(f"oracle trainer rc={rc}: {tail()}")
            odata = json.load(open(out))
            oracle_losses = odata["losses"]
            result["oracle_tail"] = oracle_losses[-5:]
            result["oracle_train_wall_s"] = odata.get("train_wall_s")
        finally:
            for p, _t in procs:
                if p.poll() is None:
                    p.kill()

    # ---- 2. chaos cluster ---------------------------------------------
    eps = [f"127.0.0.1:{free_port()}" for _ in range(2)]
    eps_csv = ",".join(eps)
    replica_ep = f"127.0.0.1:{free_port()}"
    pub_ep = f"127.0.0.1:{free_port()}"
    env = {
        "PADDLE_PS_HEARTBEAT_TIMEOUT": str(hb),
        "FLAGS_ps_replicas": "2",
        "PADDLE_PS_REPLICA_MAP": f"{eps[1]}={replica_ep}",
        # emb_stream must host as an init-on-touch LazyEmbeddingTable
        # (threshold far below 64x8) with per-row touch scores (no
        # spill tier needed) so the cron's table_shrink has a
        # shrinkable table — the run's shrink evidence
        "FLAGS_lazy_sparse_table_threshold": "1",
        "FLAGS_ps_slab_track_scores": "1",
    }
    procs = {}

    def spawn(tag, args, env_extra=None):
        p, tail = _spawn(args, os.path.join(workdir, f"{tag}.log"),
                         dict(env, **(env_extra or {})))
        procs[tag] = (p, tail)
        return p, tail

    load_stop = threading.Event()
    load_box = {"lat": [], "statuses": {}, "errors": 0}

    def load_loop(port):
        rng = np.random.RandomState(11)
        hdr = {"X-Auth-Token": auth_token}
        while not load_stop.is_set():
            cli = HttpClient("127.0.0.1", port, timeout=10.0)
            try:
                while not load_stop.is_set():
                    rid = min(n_rows - 1, int(rng.zipf(1.5)) - 1)
                    t0 = time.perf_counter()
                    try:
                        status, _obj = cli.predict(
                            {"ids": [[rid]]}, model="stream",
                            extra_headers=hdr)
                    except OSError:
                        load_box["errors"] += 1
                        break   # reconnect
                    dt = time.perf_counter() - t0
                    key = "ok" if status == 200 else str(status)
                    load_box["statuses"][key] = \
                        load_box["statuses"].get(key, 0) + 1
                    if status == 200:
                        load_box["lat"].append(dt)
                    time.sleep(0.01)
            finally:
                cli.close()

    try:
        waits = []
        for i in range(2):
            ready = os.path.join(workdir, f"ps{i}.ready")
            p, tail = spawn(
                f"ps{i}",
                [me, "stream-worker", "pserver", eps_csv, str(i), "1",
                 str(steps), ready, f"--rows={n_rows}", f"--dim={dim}"])
            waits.append((ready, p, tail))
        ready = os.path.join(workdir, "replica1.ready")
        p, tail = spawn(
            "replica1",
            [me, "stream-worker", "standby", eps_csv, "1", "1",
             str(steps), ready, f"--rows={n_rows}", f"--dim={dim}",
             f"--bind={replica_ep}", "--replica"])
        waits.append((ready, p, tail))
        for ready, p, tail in waits:
            _wait_file(ready, 120, [(p, tail)], desc=ready)

        tout = os.path.join(workdir, "t0.json")
        ckpt = os.path.join(workdir, "ckpt")
        spawn("t0",
              [me, "stream-worker", "trainer", eps_csv, "0", "1",
               str(steps), tout, f"--rows={n_rows}", f"--dim={dim}",
               f"--batch={batch}", f"--step-sleep={step_sleep}",
               f"--pub-ep={pub_ep}", f"--ckpt-dir={ckpt}"],
              {"FLAGS_ps_shrink_every_steps": str(shrink_every)})
        _wait_file(tout + ".up", 120, [procs["t0"]], desc="trainer up")

        sready = os.path.join(workdir, "server.ready")
        spawn("server",
              [me, "stream-server", "s0", eps_csv, pub_ep, sready,
               f"--rows={n_rows}", f"--dim={dim}"],
              {"FLAGS_serving_auth_token": auth_token})
        _wait_file(sready, 120, [procs["server"]], desc="serving member")
        port = int(open(sready).read().strip())

        # ---- 3. authed load + the unauthed 401 probe
        threads = [threading.Thread(target=load_loop, args=(port,),
                                    daemon=True) for _ in range(clients)]
        for th in threads:
            th.start()
        cli = HttpClient("127.0.0.1", port)
        try:
            status, obj = cli.predict({"ids": [[0]]}, model="stream")
        finally:
            cli.close()
        result["unauthed_status"] = status
        result["events"].append(("auth_probe", status,
                                 (obj or {}).get("error"), None))

        # ---- 4. pserver SIGKILL at kill_at
        prog_file = tout + ".progress"
        end = time.time() + 300
        while _progress(prog_file) < kill_at:
            p, tail = procs["t0"]
            if p.poll() is not None:
                raise RuntimeError(f"trainer died early: {tail()}")
            if time.time() > end:
                raise TimeoutError("trainer stuck before kill_at")
            time.sleep(0.05)
        t_kill = time.time()
        procs["ps1"][0].send_signal(signal.SIGKILL)
        procs["ps1"][0].wait(timeout=30)
        result["events"].append(("sigkill", eps[1], replica_ep, None))

        p, tail = procs["t0"]
        rc = p.wait(timeout=600)
        result["trainer_rc"] = rc
        result["failover_to_finish_s"] = round(time.time() - t_kill, 3)
        if rc != 0:
            raise RuntimeError(f"async trainer rc={rc}: {tail()}")
        tdata = json.load(open(tout))
        result["async_tail"] = tdata["losses"][-5:]
        result["stream_offset"] = tdata["offset"]
        result["async_train_wall_s"] = tdata.get("train_wall_s")
        result["async_steps_run"] = len(tdata["losses"])

        # post-train serving tail: keep the load running against the
        # failed-over cluster so the post-kill window carries real
        # traffic (and the subscriber drains the last invalidations
        # into the freshness histogram before the scrape)
        time.sleep(4.0)

        # freshness histogram BEFORE the load stops (live member)
        p99, cnt = _scrape_histogram_quantile(
            "127.0.0.1", port, "serving_event_freshness_seconds")
        result["freshness_p99_s"] = p99
        result["freshness_samples"] = cnt

        load_stop.set()
        for th in threads:
            th.join(timeout=30)

        # shrink evidence off the surviving slot-0 pserver: shrink_runs
        # lives in the table's tier stats (table_stats RPC), not the
        # per-method "stats" counters
        try:
            from paddle_tpu.fluid.ps_rpc import VarClient
            cli = VarClient(eps[0], connect_timeout=5.0, channels=1,
                            resolve=False)
            try:
                ts = cli.call("table_stats", name="emb_stream",
                              _rpc_timeout=10.0)
            finally:
                cli.close()
        except Exception:
            ts = {}
        shrink_runs = int(_dig(ts, "shrink_runs") or 0)
        result["shrink_runs"] = shrink_runs

        lat = load_box["lat"]
        statuses = load_box["statuses"]
        pct = percentiles_ms(lat, suffix="_ms") if lat else {}
        result["load"] = {"statuses": statuses,
                          "transport_errors": load_box["errors"],
                          "accepted": len(lat), **pct}
        bad = {k: v for k, v in statuses.items()
               if k not in ("ok", "429", "504", "503")}

        losses = np.asarray(tdata["losses"], float)
        checks = {
            "trainer_exit_0": rc == 0,
            "serving_answered": len(lat) > 0,
            "zero_typed_error_leaks": (not bad
                                       and load_box["errors"] == 0),
            "unauthed_rejected_401": result["unauthed_status"] == 401,
            "accepted_p99_bounded": bool(pct) and pct["p99_ms"] <= 500.0,
            "losses_finite": bool(np.isfinite(losses).all()),
            "shrink_cron_fired": shrink_runs >= 1,
            "freshness_bounded": (cnt > 0 and p99 is not None
                                  and p99 <= 10.0),
        }
        if oracle_losses is not None:
            otail = float(np.mean(oracle_losses[-5:]))
            atail = float(np.mean(losses[-5:]))
            result["oracle_tail_mean"] = round(otail, 5)
            result["async_tail_mean"] = round(atail, 5)
            # neighborhood, not bit-parity: unbounded staleness trades
            # exactness for throughput; the tail must still be in the
            # oracle's regime (converged, not diverged)
            checks["loss_in_oracle_neighborhood"] = \
                atail <= max(2.5 * otail, otail + 0.05)
        result["checks"] = checks
        result["ok"] = all(checks.values())
        return result
    finally:
        load_stop.set()
        for _tag, (p, _t) in procs.items():
            if p.poll() is None:
                p.kill()
        for _tag, (p, _t) in procs.items():
            try:
                p.wait(timeout=10)
            except Exception:
                pass


# ---------------------------------------------------------------------------
# wide_deep worker subcommand (pserver / standby / trainer roles)
# ---------------------------------------------------------------------------
def _flag_value(name, default=None):
    for a in sys.argv:
        if a.startswith(name + "="):
            return a.split("=", 1)[1]
    return default


def run_worker():
    role, eps, idx, trainers, steps, outfile = sys.argv[2:8]
    idx, trainers, steps = int(idx), int(trainers), int(steps)
    sparse_dim = int(_flag_value("--sparse-dim", 200) or 200)
    batch = int(_flag_value("--batch", 32) or 32)
    step_sleep = float(_flag_value("--step-sleep", 0) or 0)

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid.transpiler import DistributeTranspiler
    from paddle_tpu.models import wide_deep

    def build():
        return wide_deep.build_wide_deep_program(
            num_dense=4, num_slots=3, sparse_dim=sparse_dim,
            embedding_dim=4, hidden=(16, 16), lr=1e-2, with_auc=False,
            is_distributed=True, optimizer=fluid.optimizer.SGD(1e-2))

    main, startup, feeds, loss, _auc = build()
    from paddle_tpu.fluid.transpiler import DistributeTranspilerConfig
    cfg = DistributeTranspilerConfig()
    if "--async-overlap" in sys.argv:
        # ps_round comm tail (docs/PS_DATA_PLANE.md "Async overlap");
        # FLAGS_async_staleness rides the env into this subprocess
        cfg.async_overlap = True
    t = DistributeTranspiler(cfg)
    with fluid.program_guard(main, startup):
        t.transpile(trainer_id=idx if role == "trainer" else 0,
                    pservers=eps, trainers=trainers, sync_mode=True,
                    program=main, startup_program=startup)
    exe = fluid.Executor()
    scope = core.Scope()
    if role in ("pserver", "standby"):
        ep = eps.split(",")[idx]
        if role == "standby":
            bind = _flag_value("--bind")
            pprog = t.get_pserver_program(
                ep, bind_endpoint=bind, standby=True,
                replica_of=ep if "--replica" in sys.argv else "")
        else:
            pprog = t.get_pserver_program(ep)
        pstart = t.get_startup_program(ep, pprog)
        with fluid.scope_guard(scope):
            exe.run(pstart)
            open(outfile, "w").write("ready")
            exe.run(pprog)
        return

    from paddle_tpu.fluid.ps_rpc import VarClient, WorkerHeartBeat
    hb_interval = max(0.25, float(
        os.environ.get("PADDLE_PS_HEARTBEAT_TIMEOUT", 60.0)) / 4)
    beat = WorkerHeartBeat(eps.split(","), idx,
                           interval=hb_interval).start()
    nb = wide_deep.ctr_reader(batch, num_dense=4, num_slots=3,
                              sparse_dim=sparse_dim, seed=idx)
    losses = []
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            prog = t.get_trainer_program()
            for s in range(steps):
                (lv,) = exe.run(prog, feed=nb(), fetch_list=[loss])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
                with open(outfile + ".progress", "a") as pf:
                    pf.write(f"{s} {losses[-1]!r}\n")
                if step_sleep:
                    time.sleep(step_sleep)
            # flush the async-overlap staleness pipe before the
            # pservers are released (no-op in plain sync mode)
            from paddle_tpu.fluid.communicator import drain_async_rounds
            drain_async_rounds()
    finally:
        beat.stop()
    json.dump(losses, open(outfile, "w"))


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "worker":
        run_worker()
        return 0
    if len(sys.argv) > 1 and sys.argv[1] == "serving-member":
        run_serving_member()
        return 0
    if len(sys.argv) > 1 and sys.argv[1] == "stream-worker":
        run_stream_worker()
        return 0
    if len(sys.argv) > 1 and sys.argv[1] == "stream-server":
        run_stream_server()
        return 0
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scenario", default="full",
                    choices=["drain_rejoin", "failover", "full",
                             "serving_fleet", "streaming"])
    ap.add_argument("--model", default="linear",
                    choices=["linear", "wide_deep"])
    ap.add_argument("--trainers", type=int, default=3)
    ap.add_argument("--pservers", type=int, default=2)
    ap.add_argument("--steps", type=int, default=None,
                    help="default 14 (membership) / 40 (streaming)")
    ap.add_argument("--hb", type=float, default=2.0)
    ap.add_argument("--drain-at", type=int, default=3)
    ap.add_argument("--rejoin-at", type=int, default=7)
    ap.add_argument("--kill-at", type=int, default=None,
                    help="default 5 (membership) / 15 (streaming)")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--no-oracle", action="store_true")
    ap.add_argument("--no-bench", action="store_true",
                    help="streaming: skip the BENCH_LOCAL.json row")
    ap.add_argument("--trace-dir", default=None,
                    help="stream FLAGS_trace_dir shards from every "
                         "chaos process and run a tools/timeline.py "
                         "merge smoke over them afterwards "
                         "(docs/OBSERVABILITY.md)")
    args = ap.parse_args()
    workdir = args.workdir or os.path.join(
        tempfile.gettempdir(), f"chaos_ps_{int(time.time())}")
    if args.trace_dir:
        # subprocesses inherit the env; the chaos trainers/pservers
        # each stream a shard the merge smoke below combines
        os.makedirs(args.trace_dir, exist_ok=True)
        os.environ["FLAGS_trace_dir"] = args.trace_dir
    if args.scenario == "serving_fleet":
        res = run_serving_fleet_scenario(workdir, hb=args.hb)
        print(json.dumps({k: v for k, v in res.items()
                          if k != "load"}, indent=1, default=str))
        print("load:", json.dumps(res.get("load", {}), default=str))
        return 0 if res.get("ok") else 1
    if args.scenario == "streaming":
        res = run_streaming_scenario(workdir, steps=args.steps or 40,
                                     hb=args.hb,
                                     kill_at=args.kill_at or 15,
                                     with_oracle=not args.no_oracle)
        print(json.dumps(res, indent=1, default=str))
        if res.get("ok") and not args.no_bench:
            # acceptance contract: the measured freshness p99 is
            # RECORDED, not just printed — append a BENCH_LOCAL row
            path = os.path.join(REPO, "BENCH_LOCAL.json")
            try:
                bl = json.load(open(path))
            except (OSError, ValueError):
                bl = {"note": "", "rows": []}
            bl.setdefault("rows", []).append({
                "metric": "streaming_chaos_freshness_p99",
                "value": res.get("freshness_p99_s"),
                "unit": "s (bucket upper bound)",
                "vs_baseline": 1.0,
                "ok": res.get("ok"),
                "freshness_samples": res.get("freshness_samples"),
                "p99_ms": (res.get("load") or {}).get("p99_ms"),
                "statuses": (res.get("load") or {}).get("statuses"),
                "shrink_runs": res.get("shrink_runs"),
                "async_tail_mean": res.get("async_tail_mean"),
                "oracle_tail_mean": res.get("oracle_tail_mean"),
                "note": "tools/chaos_ps.py --scenario streaming: "
                        "async stream train+serve, pserver SIGKILL + "
                        "shrink cron mid-run; 1-core box",
            })
            json.dump(bl, open(path, "w"), indent=1)
        return 0 if res.get("ok") else 1
    res = run_scenario(args.scenario, workdir, model=args.model,
                       trainers=args.trainers, n_pservers=args.pservers,
                       steps=args.steps or 14, hb=args.hb,
                       drain_at=args.drain_at, rejoin_at=args.rejoin_at,
                       kill_at=args.kill_at or 5,
                       with_oracle=not args.no_oracle)
    print(json.dumps(
        {k: v for k, v in res.items() if "losses" not in k}, indent=1,
        default=str))
    if args.trace_dir:
        # timeline-merge smoke: the shards the run just streamed must
        # combine into one clock-corrected timeline (exit non-zero on
        # an empty/unmergeable dir — the chaos driver doubles as the
        # obs plane's multiprocess canary)
        from tools import timeline as _timeline
        summary = _timeline.merge_shards(
            args.trace_dir,
            out=os.path.join(args.trace_dir, "timeline.json"))
        print("trace merge:", json.dumps(summary, indent=1))
        if summary["n_events"] == 0:
            print("trace merge produced ZERO events — shards empty?")
            return 1
    if res.get("oracle_losses") is not None:
        print("bit_identical:", res["bit_identical"])
        if not res["bit_identical"]:
            for t, (a, b) in enumerate(zip(res["losses"],
                                           res["oracle_losses"])):
                if a != b:
                    print(f"trainer {t} diverged: chaos={a[-3:]} "
                          f"oracle={b[-3:]}")
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
