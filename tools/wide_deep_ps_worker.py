"""Wide&Deep CTR over the distributed parameter-server plane at 1e9+
embedding parameters (reference CTR job: fleet downpour over
fleet_wrapper.h DownpourSparseTable). Used by `bench.py wide_deep_1b`
(trainer in-process, pservers as subprocesses of this module).

The per-slot tables are marked is_distributed; above
FLAGS_lazy_sparse_table_threshold they are hosted on every pserver as
row-sharded init-on-touch LazyEmbeddingTable, so the 1e9-parameter
logical size costs only O(touched rows) host RAM.
"""
import os
import sys

os.environ.setdefault("FLAGS_lazy_sparse_table_threshold", "1000000")
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _fluid():
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.fluid as fluid
    return fluid


def build(sparse_dim, embedding_dim=16, num_dense=13, num_slots=26,
          hidden=(64, 64)):
    fluid = _fluid()
    from paddle_tpu.models import wide_deep
    # SGD: pserver-side row updates are plain SGD on the sparse plane
    return wide_deep.build_wide_deep_program(
        num_dense=num_dense, num_slots=num_slots, sparse_dim=sparse_dim,
        embedding_dim=embedding_dim, hidden=hidden, lr=1e-3,
        is_distributed=True,
        optimizer=fluid.optimizer.SGD(1e-3))


def transpile(main, startup, eps, trainer_id=0, trainers=1):
    fluid = _fluid()
    from paddle_tpu.fluid.transpiler import (DistributeTranspiler,
                                             DistributeTranspilerConfig)
    # PADDLE_TPU_WD_GEO=1 flips the cluster into geo-SGD delta-sync mode
    # (bench.py wide_deep_geo WAN lanes): local optimizer + periodic
    # geo_sgd_send, pservers apply deltas on arrival. Env-keyed so the
    # pserver subprocesses of ONE bench lane agree with the in-process
    # trainer without new argv plumbing.
    geo = os.environ.get("PADDLE_TPU_WD_GEO") == "1"
    cfg = DistributeTranspilerConfig()
    if geo:
        cfg.geo_sgd_mode = True
        cfg.geo_sgd_need_push_nums = int(
            os.environ.get("PADDLE_TPU_WD_GEO_PUSH_NUMS", "8"))
    t = DistributeTranspiler(cfg)
    with fluid.program_guard(main, startup):
        t.transpile(trainer_id=trainer_id, pservers=eps, trainers=trainers,
                    sync_mode=not geo, program=main,
                    startup_program=startup)
    return t


def run_pserver(eps, idx, sparse_dim, trainers=1):
    fluid = _fluid()
    from paddle_tpu.fluid import core
    main, startup, feeds, loss, auc = build(sparse_dim)
    t = transpile(main, startup, eps, trainers=trainers)
    ep = eps.split(",")[idx]
    pprog = t.get_pserver_program(ep)
    pstart = t.get_startup_program(ep, pprog)
    exe = fluid.Executor()
    scope = core.Scope()
    with fluid.scope_guard(scope):
        exe.run(pstart)
        print("PSERVER_READY", flush=True)
        exe.run(pprog)  # blocks until stop rpc


def run_trainer(eps, trainer_id, trainers, sparse_dim, batch, steps,
                warmup, outfile, window_k=1):
    """Subprocess trainer for the multi-trainer bench row: trains its
    shard of the deterministic batch stream against the shared PS plane
    and writes its samples/sec. ``window_k > 1`` feeds a [K, ...] stack
    of K distinct batches per run (the async-overlap lanes' shape —
    the executor's window fallback staggers sparse prefetch across the
    slices); ``warmup``/``steps`` stay TOTAL step counts so the sync
    plane's per-round barrier accounting matches trainer 0's."""
    import json
    import time

    import numpy as np

    fluid = _fluid()
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid.communicator import drain_async_rounds
    from paddle_tpu.fluid.ps_rpc import WorkerHeartBeat
    from paddle_tpu.models import wide_deep

    main, startup, feeds, loss, auc = build(sparse_dim)
    t = transpile(main, startup, eps, trainer_id=trainer_id,
                  trainers=trainers)
    prog = t.get_trainer_program()
    exe = fluid.Executor()
    scope = core.Scope()
    nb = wide_deep.ctr_reader(batch, num_dense=13, num_slots=26,
                              sparse_dim=sparse_dim, seed=trainer_id)
    window_k = max(1, int(window_k))
    if window_k > 1:
        assert steps % window_k == 0 and warmup % window_k == 0, \
            (steps, warmup, window_k)
        batches = [nb() for _ in range(window_k)]
        feed = {n: np.stack([b[n] for b in batches])
                for n in batches[0]}
        kw = {"n_steps": window_k}
    else:
        feed = nb()
        kw = {}
    beat = WorkerHeartBeat(eps.split(","), trainer_id, interval=0.5).start()
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(warmup // window_k):
                exe.run(prog, feed=feed, fetch_list=[loss], **kw)
            t0 = time.perf_counter()
            for _ in range(steps // window_k):
                exe.run(prog, feed=feed, fetch_list=[loss], **kw)
            # in-flight async rounds are part of the measured work
            drain_async_rounds()
            dt = time.perf_counter() - t0
    finally:
        beat.stop()
    with open(outfile, "w") as f:
        json.dump({"samples_per_sec": batch * steps / dt,
                   "trainer_id": trainer_id}, f)


if __name__ == "__main__":
    role = sys.argv[1]
    if role == "pserver":
        run_pserver(sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
                    int(sys.argv[5]) if len(sys.argv) > 5 else 1)
    elif role == "trainer":
        run_trainer(sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
                    int(sys.argv[5]), int(sys.argv[6]), int(sys.argv[7]),
                    int(sys.argv[8]), sys.argv[9],
                    int(sys.argv[10]) if len(sys.argv) > 10 else 1)
    else:
        raise SystemExit(f"unknown role {role!r}")
