#!/usr/bin/env python
"""Timeline viewer/merger (reference: tools/timeline.py — converts profiler
protobufs to chrome://tracing).

Two modes:

* **legacy profile merge** — our profiler writes chrome-trace JSON per
  process; this merges several profile files into one timeline with
  distinct pids::

      python tools/timeline.py --profile_path p0.json,p1.json \
          --timeline_path timeline.json

  Also accepts the reference's "name=file" form: trainer0=prof0.json.

* **cluster trace-shard merge** (PR 10, docs/OBSERVABILITY.md) — every
  process running with ``FLAGS_trace_dir`` streams a bounded
  chrome-trace shard with RAW ``time.perf_counter`` timestamps plus the
  monotonic clock offsets it measured against its peers in the ps_rpc
  ``_hello`` handshake. ``merge`` aligns all shards onto ONE reference
  clock (measured offsets first, wall-clock anchor fallback), labels
  each process row, and optionally filters to a single trace id::

      python tools/timeline.py merge --trace_dir /tmp/shards \
          --out timeline.json [--trace_id abc123] [--ref trainer0]

  The result opens in chrome://tracing / Perfetto; ``args.trace_id`` on
  every span is what links a trainer's rpc spans to the owning
  pserver's handler spans across processes.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple


def load_profile(path: str):
    with open(path) as f:
        data = json.load(f)
    if "traceEvents" not in data:
        raise ValueError(f"{path}: not a chrome-trace JSON")
    return data["traceEvents"]


def merge(profiles, timeline_path: str):
    out = {"traceEvents": [], "displayTimeUnit": "ms"}
    for rank, (name, path) in enumerate(profiles):
        events = load_profile(path)
        for e in events:
            e = dict(e)
            e["pid"] = rank
            out["traceEvents"].append(e)
        # process-name metadata row so chrome://tracing labels each worker
        out["traceEvents"].append({
            "name": "process_name", "ph": "M", "pid": rank,
            "args": {"name": name}})
    with open(timeline_path, "w") as f:
        json.dump(out, f)
    print(f"merged {len(profiles)} profile(s) -> {timeline_path}")


# ---------------------------------------------------------------------------
# cluster trace-shard merge
# ---------------------------------------------------------------------------
def load_shards(trace_dir: str) -> List[dict]:
    """Load every ``trace-*.json`` shard under ``trace_dir``; each is
    {"traceEvents": [...], "metadata": {...}} as written by
    fluid.telemetry's shard streamer."""
    shards = []
    for path in sorted(glob.glob(os.path.join(trace_dir,
                                              "trace-*.json"))):
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            print(f"[timeline] skipping unreadable shard {path}: {e!r}",
                  file=sys.stderr)
            continue
        if "traceEvents" not in data or "metadata" not in data:
            print(f"[timeline] skipping {path}: not a trace shard",
                  file=sys.stderr)
            continue
        data["path"] = path
        shards.append(data)
    return shards


def _pick_reference(shards: List[dict],
                    ref: Optional[str]) -> dict:
    """The shard whose clock everything aligns to. ``--ref`` matches a
    role substring; default prefers a trainer shard (trainers measured
    the offsets — they dial every pserver) then falls back to the
    first shard."""
    if ref:
        for s in shards:
            if ref in (s["metadata"].get("role") or ""):
                return s
        raise ValueError(
            f"--ref {ref!r} matches no shard role; roles: "
            f"{[s['metadata'].get('role') for s in shards]}")
    for s in shards:
        role = s["metadata"].get("role") or ""
        if "trainer" in role:
            return s
    return shards[0]


def _shard_delta_us(shard: dict, refshard: dict) -> Tuple[float, str]:
    """Microseconds to ADD to this shard's raw perf timestamps to land
    on the reference shard's clock, plus the source of the estimate.

    Priority: the reference's measured offset to this shard's endpoint
    (hello handshake, NTP-style) > this shard's measured offset to the
    reference's endpoint (sign flipped) > wall-clock anchor pair
    (exact on one host — perf and wall tick together; cross-host it is
    only as good as NTP)."""
    if shard is refshard:
        return 0.0, "reference"
    ref_meta, meta = refshard["metadata"], shard["metadata"]
    ep = meta.get("endpoint")
    ref_offsets = ref_meta.get("peer_offsets") or {}
    if ep and ep in ref_offsets:
        # offset = peer_perf - ref_perf ⇒ peer ts - offset = ref ts
        return -float(ref_offsets[ep]["offset_us"]), "hello-offset"
    ref_ep = ref_meta.get("endpoint")
    offsets = meta.get("peer_offsets") or {}
    if ref_ep and ref_ep in offsets:
        return float(offsets[ref_ep]["offset_us"]), "hello-offset-rev"
    wall_delta = ((meta["anchor_wall_us"] - meta["anchor_perf_us"])
                  - (ref_meta["anchor_wall_us"]
                     - ref_meta["anchor_perf_us"]))
    return wall_delta, "wall-anchor"


def merge_shards(trace_dir: str, out: Optional[str] = None,
                 trace_id: Optional[str] = None,
                 ref: Optional[str] = None) -> dict:
    """Merge a FLAGS_trace_dir's shards into one clock-corrected
    timeline. Returns a summary dict (and writes ``out`` when given):

      {"n_shards", "n_events", "out",
       "processes": {role: {"delta_us", "source", "n_events",
                            "dropped_events"}}}
    """
    shards = load_shards(trace_dir)
    if not shards:
        raise ValueError(f"no trace-*.json shards under {trace_dir!r}")
    refshard = _pick_reference(shards, ref)
    merged: List[dict] = []
    summary: Dict[str, dict] = {}
    for rank, shard in enumerate(shards):
        meta = shard["metadata"]
        role = meta.get("role") or f"proc{meta.get('pid', rank)}"
        if role in summary:
            # a respawned process reuses its role (chaos rejoin): keep
            # BOTH summary entries — a clock problem or event drop in
            # the first incarnation must stay visible
            role = f"{role}#{meta.get('pid', rank)}"
        delta_us, source = _shard_delta_us(shard, refshard)
        kept = 0
        for e in shard["traceEvents"]:
            if trace_id is not None and \
                    (e.get("args") or {}).get("trace_id") != trace_id:
                continue
            e = dict(e)
            e["pid"] = rank
            e["ts"] = float(e["ts"]) + delta_us
            merged.append(e)
            kept += 1
        merged.append({"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": role}})
        summary[role] = {"delta_us": delta_us, "source": source,
                         "n_events": kept,
                         "dropped_events": meta.get("dropped_events",
                                                    0)}
    # rebase to zero so chrome://tracing doesn't render hour-long
    # leading dead space (perf_counter epochs are arbitrary)
    spans = [e for e in merged if e.get("ph") == "X"]
    if spans:
        t0 = min(e["ts"] for e in spans)
        for e in spans:
            e["ts"] -= t0
    spans.sort(key=lambda e: e["ts"])
    result = {"n_shards": len(shards), "n_events": len(spans),
              "out": out, "processes": summary}
    if out:
        with open(out, "w") as f:
            json.dump({"traceEvents": merged,
                       "displayTimeUnit": "ms"}, f)
    return result


def _main_merge(argv) -> int:
    p = argparse.ArgumentParser(
        prog="timeline.py merge",
        description="merge FLAGS_trace_dir shards into one "
                    "clock-corrected timeline")
    p.add_argument("--trace_dir", required=True)
    p.add_argument("--out", default="timeline.json")
    p.add_argument("--trace_id", default=None,
                   help="keep only spans of this trace id")
    p.add_argument("--ref", default=None,
                   help="role substring of the reference-clock shard "
                        "(default: a trainer shard)")
    args = p.parse_args(argv)
    summary = merge_shards(args.trace_dir, out=args.out,
                           trace_id=args.trace_id, ref=args.ref)
    print(json.dumps(summary, indent=2))
    print(f"merged {summary['n_shards']} shard(s), "
          f"{summary['n_events']} event(s) -> {args.out}")
    return 0


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "merge":
        raise SystemExit(_main_merge(sys.argv[2:]))
    p = argparse.ArgumentParser()
    p.add_argument("--profile_path", required=True,
                   help="comma-separated profile files; each may be "
                        "'name=path' or bare 'path'")
    p.add_argument("--timeline_path", default="timeline.json")
    args = p.parse_args()
    profiles = []
    for item in args.profile_path.split(","):
        item = item.strip()
        if not item:
            continue
        if "=" in item:
            name, path = item.split("=", 1)
        else:
            name, path = f"worker{len(profiles)}", item
        profiles.append((name, path))
    merge(profiles, args.timeline_path)


if __name__ == "__main__":
    main()
