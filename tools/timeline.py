#!/usr/bin/env python
"""Timeline viewer/merger (reference: tools/timeline.py — converts profiler
protobufs to chrome://tracing). Our profiler already writes chrome-trace
JSON; this tool merges several profile files (e.g. one per worker) into one
timeline with distinct pids, ready for chrome://tracing or Perfetto.

Usage:
    python tools/timeline.py --profile_path p0.json,p1.json \
        --timeline_path timeline.json
Also accepts the reference's "name=file" form: trainer0=prof0.json.
"""
from __future__ import annotations

import argparse
import json


def load_profile(path: str):
    with open(path) as f:
        data = json.load(f)
    if "traceEvents" not in data:
        raise ValueError(f"{path}: not a chrome-trace JSON")
    return data["traceEvents"]


def merge(profiles, timeline_path: str):
    out = {"traceEvents": [], "displayTimeUnit": "ms"}
    for rank, (name, path) in enumerate(profiles):
        events = load_profile(path)
        for e in events:
            e = dict(e)
            e["pid"] = rank
            out["traceEvents"].append(e)
        # process-name metadata row so chrome://tracing labels each worker
        out["traceEvents"].append({
            "name": "process_name", "ph": "M", "pid": rank,
            "args": {"name": name}})
    with open(timeline_path, "w") as f:
        json.dump(out, f)
    print(f"merged {len(profiles)} profile(s) -> {timeline_path}")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--profile_path", required=True,
                   help="comma-separated profile files; each may be "
                        "'name=path' or bare 'path'")
    p.add_argument("--timeline_path", default="timeline.json")
    args = p.parse_args()
    profiles = []
    for item in args.profile_path.split(","):
        item = item.strip()
        if not item:
            continue
        if "=" in item:
            name, path = item.split("=", 1)
        else:
            name, path = f"worker{len(profiles)}", item
        profiles.append((name, path))
    merge(profiles, args.timeline_path)


if __name__ == "__main__":
    main()
