"""Flash-attention hardware bring-up smoke: compile the Pallas kernels
via Mosaic (NO interpret mode), check on-chip parity vs the einsum path,
and sweep block sizes — one JSON row per configuration.

This is the first-tunnel-window script (VERDICT r2 item 2): everything
that can fail on first Mosaic contact — scratch shapes, SMEM scalar
handling, dimension_semantics, VMEM budgets — is exercised here in one
command so a live TPU window produces data, not debugging. Reference
counterpart: operators/fused/multihead_matmul_op.cu is the reference's
fused fast path; operators/benchmark/op_tester.cc is its measure-don't-
assert harness.

Usage:
    python -m tools.flash_smoke            # full sweep (TPU) / tiny (CPU)
    python bench.py flash                  # same, through the bench entry

Per-config JSON row fields: seq_len, blk_q, blk_k, dtype, causal,
dropout, fwd_ms, fwdbwd_ms, tflops_fwd, vmem_kb_est, max_err_fwd,
max_err_dq/dk/dv, dropout_deterministic, status ('ok' | 'parity_fail' |
'compile_error'), error.
"""
from __future__ import annotations

import contextlib
import json
import time
import traceback

import numpy as np


def _vmem_kb_estimate(blk_q, blk_k, D, bwd=False):
    """Analytic resident-VMEM estimate per grid step (f32 working set):
    fwd: q, k, v tiles + acc[blk_q,D] + m/l[blk_q,128] + o tile.
    bwd adds do/lse/delta tiles and the dk/dv (or dq) accumulators."""
    f = 4  # f32 working set (inputs are upcast in-kernel)
    fwd = (blk_q * D + 2 * blk_k * D) * f            # q,k,v tiles
    fwd += blk_q * D * f                             # acc scratch
    fwd += 2 * blk_q * 128 * f                       # m, l scratch
    fwd += blk_q * D * f                             # o tile
    if not bwd:
        return fwd / 1024.0
    b = blk_q * D * f                                # do tile
    b += 2 * blk_q * 128 * f                         # lse/delta (LANES)
    b += 2 * blk_k * D * f                           # dk/dv accumulators
    return (fwd + b) / 1024.0


def _timed_scan(fn, q, k, v, iters):
    """Time ``iters`` executions inside ONE dispatched lax.scan. A
    host-side timing loop pays the tunnel's per-dispatch RTT (~10ms)
    every call — at these shapes that is ~100× the kernel itself, so it
    measures the wire, not the MXU. The scan carry threads a tiny data
    dependency through q so XLA cannot hoist the loop-invariant body out
    of the loop. Returns ms per iteration."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def body(c, _):
        out = fn(q + c, k, v)
        leaf = out[0] if isinstance(out, (tuple, list)) else out
        return (leaf.ravel()[0] * 1e-20).astype(q.dtype), None

    @jax.jit
    def many():
        c, _ = lax.scan(body, jnp.zeros((), q.dtype), None, length=iters)
        return c

    many().block_until_ready()  # compile + warm
    t0 = time.perf_counter()
    many().block_until_ready()
    return (time.perf_counter() - t0) / iters * 1e3


def config_key(row_or_s, blk_q=None, blk_k=None, causal=False, dropout=0.0):
    """Stable identity of a sweep configuration (for resume-after-stall:
    a tunnel window can close mid-sweep, and re-running must skip configs
    that already produced an ok row)."""
    if isinstance(row_or_s, dict):
        r = row_or_s
        return (r["seq_len"], r["blk_q"], r["blk_k"],
                bool(r.get("causal")), float(r.get("dropout", 0.0)))
    return (row_or_s, blk_q, blk_k, bool(causal), float(dropout))


def kernel_fingerprint():
    """Short hash of the kernel + harness sources — banked rows from an
    older kernel must not satisfy (or pollute) a resumed sweep."""
    import hashlib
    import os
    from paddle_tpu.ops.pallas import flash_attention as fa
    h = hashlib.sha1()
    for path in (fa.__file__, os.path.abspath(__file__)):
        h.update(open(path, "rb").read())
    return h.hexdigest()[:12]


def run_config(S, blk_q, blk_k, *, B=4, H=8, D=64, dtype="bfloat16",
               causal=False, dropout=0.0, steps=None, interpret=False):
    """Compile + parity-check + time one (S, blk_q, blk_k) config.
    ``steps`` overrides the scan-timing iteration count. Returns the
    JSON row dict (fwd_ms/fwdbwd_ms from the device-side scan,
    dispatch_ms = single-dispatch wall time incl. tunnel RTT);
    never raises."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import flash_attention as fa

    row = {"seq_len": S, "blk_q": blk_q, "blk_k": blk_k, "dtype": dtype,
           "batch": B, "heads": H, "head_dim": D, "causal": causal,
           "dropout": dropout, "kfp": kernel_fingerprint(),
           "vmem_kb_est": round(_vmem_kb_estimate(blk_q, blk_k, D, True), 1)}
    if S % blk_q or S % blk_k:
        row["ragged"] = True  # boundary blocks masked in-kernel
    # the custom-vjp backward kernels are traced when the grad is built,
    # AFTER the wrapped forward returns — so the interpret/block
    # overrides must span the whole computation, not just the fwd call
    ictx = fa.interpret_guard() if interpret else contextlib.nullcontext()
    try:
        with ictx, fa.block_override(blk_q, blk_k):
            rng = np.random.RandomState(0)
            jdt = jnp.dtype(dtype)
            q, k, v = (jnp.asarray(rng.randn(B, H, S, D) * 0.3, jdt)
                       for _ in range(3))
            scale = 1.0 / np.sqrt(D)
            seed = jnp.asarray([1234], jnp.int32)

            def flash(q, k, v):
                return fa.flash_attention(q, k, v, scale, causal=causal,
                                          dropout_rate=dropout,
                                          dropout_seed=seed)

            def loss(q, k, v):
                return jnp.sum(flash(q, k, v).astype(jnp.float32) ** 2)

            fwd = jax.jit(flash)
            grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

            # --- compile + numerics ---------------------------------
            o = np.asarray(fwd(q, k, v), np.float32)
            dq, dk, dv = (np.asarray(t, np.float32)
                          for t in grad(q, k, v))

            if dropout == 0.0:
                o_ref = np.asarray(
                    fa._ref_attention(q, k, v, scale, causal), np.float32)

                def loss_ref(q, k, v):
                    return jnp.sum(fa._ref_attention(
                        q, k, v, scale, causal).astype(jnp.float32) ** 2)

                rq, rk, rv = (np.asarray(t, np.float32) for t in
                              jax.jit(jax.grad(loss_ref,
                                               argnums=(0, 1, 2)))(q, k, v))
                scale_o = max(1.0, float(np.abs(o_ref).max()))
                row["max_err_fwd"] = float(np.abs(o - o_ref).max()
                                           / scale_o)
                for nm, a, b in (("dq", dq, rq), ("dk", dk, rk),
                                 ("dv", dv, rv)):
                    s = max(1.0, float(np.abs(b).max()))
                    row[f"max_err_{nm}"] = float(np.abs(a - b).max() / s)
                # bf16 inputs, f32 accumulation: 2e-2 relative headroom
                tol = 2e-2 if jdt == jnp.bfloat16 else 2e-3
                ok = all(row[f"max_err_{n}"] < tol
                         for n in ("fwd", "dq", "dk", "dv"))
            else:
                # dropout parity has no closed-form twin on-chip; the
                # checks are determinism (same seed → identical bits)
                # and finite grads
                o2 = np.asarray(fwd(q, k, v), np.float32)
                row["dropout_deterministic"] = bool((o == o2).all())
                ok = (row["dropout_deterministic"]
                      and all(np.isfinite(t).all()
                              for t in (o, dq, dk, dv)))

            # --- timing (device-side scan: one dispatch, many iters) --
            iters = steps or (2 if interpret else 20)
            row["fwd_ms"] = round(_timed_scan(flash, q, k, v, iters), 3)
            row["fwdbwd_ms"] = round(_timed_scan(
                jax.grad(loss, argnums=(0, 1, 2)), q, k, v, iters), 3)
            # single-dispatch wall time, for the tunnel-latency record
            t0 = time.perf_counter()
            fwd(q, k, v).block_until_ready()
            row["dispatch_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
            # 4·B·H·S²·D MACs fwd (QKᵀ + PV) → 2 flops/MAC
            flops = 4 * B * H * S * S * D * 2 * (0.5 if causal else 1.0)
            row["tflops_fwd"] = round(flops / (row["fwd_ms"] * 1e-3) / 1e12,
                                      2)
            row["status"] = "ok" if ok else "parity_fail"
    except Exception as e:  # compile errors are DATA here, not crashes
        row["status"] = "compile_error"
        row["error"] = repr(e)[:400]
        row["traceback_tail"] = traceback.format_exc()[-600:]
    return row


def sweep_plan(on_tpu):
    """The full config list, as (S, bq, bk, causal, dropout) tuples."""
    plan = []
    if on_tpu:
        # 128/256 first: the headline bench (bert seq_len=128, D=64)
        # must get a tuned row even if the window closes mid-sweep
        seqs, blocks = [128, 256, 512, 1024, 2048], [128, 256, 512]
        dchecks = [(512, 128, 128)]
    else:
        seqs, blocks = [128, 256], [64, 128]
        dchecks = [(128, 64, 64)]
    for S in seqs:
        for bq in blocks:
            for bk in blocks:
                if bq > S or bk > S:
                    continue
                plan.append((S, bq, bk, False, 0.0))
    # causal + dropout + ragged legs on the best-known block config
    for (S, bq, bk) in dchecks:
        plan.append((S, bq, bk, True, 0.0))
        plan.append((S, bq, bk, False, 0.1))
        # ragged boundary block (S not a multiple of the block)
        plan.append((S - S // 4 - 3, bq, bk, False, 0.0))
    return plan


def sweep(on_tpu, emit=print, done=frozenset()):
    """Full bring-up sweep; configs whose key is in ``done`` are skipped
    (resume after a tunnel stall). On CPU the kernels run via the
    interpreter at tiny shapes — that validates THIS harness end-to-end,
    not Mosaic."""
    rows = []
    for (S, bq, bk, causal, dropout) in sweep_plan(on_tpu):
        if config_key(S, bq, bk, causal, dropout) in done:
            continue
        r = run_config(S, bq, bk, causal=causal, dropout=dropout,
                       interpret=not on_tpu)
        rows.append(r)
        emit(json.dumps(r))
    return rows


def tuning_path():
    """The ONE location of the banked block-tuning table — the kernel's
    `_tuned_blocks` and `write_tuning` both resolve it here, so they
    cannot silently diverge."""
    import os
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "flash_blocks.json")


def write_tuning(rows, path=None):
    """Bank the fastest (blk_q, blk_k) per (seq_len, head_dim)
    (training criterion: fwd+bwd ms; clean non-causal/no-dropout/
    non-ragged rows only), stamped with the kernel fingerprint so a
    kernel edit invalidates the table like it invalidates the row bank.
    `flash_attention._tuned_blocks` picks these up, so every kernel call
    after a hardware sweep runs the measured-best blocks."""
    best = {}
    for r in rows:
        if r.get("status") != "ok" or r.get("causal") \
                or r.get("dropout") or r.get("ragged"):
            continue
        if "fwdbwd_ms" not in r:
            continue
        key = (int(r["seq_len"]), int(r.get("head_dim", 64)))
        cur = best.get(key)
        if cur is None or r["fwdbwd_ms"] < cur["fwdbwd_ms"]:
            best[key] = r
    if not best:
        return False
    path = path or tuning_path()
    with open(path, "w") as f:
        json.dump({"kfp": kernel_fingerprint(),
                   "entries": {f"{s}:{d}": [int(r["blk_q"]),
                                            int(r["blk_k"])]
                               for (s, d), r in sorted(best.items())}},
                  f, indent=1)
    # the kernel's lazy cache may hold the pre-file (empty) table
    from paddle_tpu.ops.pallas import flash_attention as fa
    fa._TUNED = None
    return True


def summarize(rows, backend):
    ok = [r for r in rows if r.get("status") == "ok"]
    fails = [r for r in rows if r.get("status") in ("parity_fail",
                                                    "compile_error")]
    best = max(ok, key=lambda r: r.get("tflops_fwd", 0.0), default=None)
    out = {"metric": "flash_attention_best_tflops_fwd",
           "value": best["tflops_fwd"] if best else 0.0, "unit": "TFLOP/s",
           "vs_baseline": 1.0, "configs_ok": len(ok),
           "configs_failed": len(fails), "backend": backend}
    if best:
        out["best_config"] = {k: best[k] for k in
                              ("seq_len", "blk_q", "blk_k", "fwd_ms",
                               "fwdbwd_ms")}
    if fails:
        out["first_failure"] = {k: fails[0].get(k) for k in
                                ("seq_len", "blk_q", "blk_k", "status",
                                 "error")}
    return out


def main():
    # bounded backend probe (the axon tunnel can hang jax.devices()
    # forever) — reuse the bench harness's retrying subprocess probe
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import _ensure_backend
    backend = _ensure_backend()
    rows = sweep(on_tpu=backend not in ("cpu", "cpu_fallback"))
    print(json.dumps(summarize(rows, backend)))


if __name__ == "__main__":
    main()
