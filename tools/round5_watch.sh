#!/bin/bash
# Round-5 tunnel watcher: probe until a TPU window opens, then run the
# first-contact plan immediately; repeat for the whole round so a second
# window is spent iterating (flash sweep tail, batch ladder) rather than
# being missed. All output goes to tools/round5_watch.log.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
DEADLINE=$(( $(date +%s) + ${ROUND5_WATCH_HOURS:-11} * 3600 ))
cd "$REPO"
n=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  n=$((n + 1))
  left_h=$(( (DEADLINE - $(date +%s)) / 3600 ))
  echo "=== watch cycle $n ($(date -u +%FT%TZ), ~${left_h}h left) ==="
  python tools/tpu_probe_loop.py 180 "$(( (DEADLINE - $(date +%s)) / 3600 + 1 ))"
  rc=$?
  if [ "$rc" -eq 0 ]; then
    echo "=== TUNNEL LIVE — running first_contact ($(date -u +%FT%TZ)) ==="
    FIRST_CONTACT_SKIP_PROBE=1 python tools/first_contact.py
    echo "=== first_contact done rc=$? ($(date -u +%FT%TZ)) ==="
    sleep 20
  elif [ "$rc" -eq 3 ]; then
    echo "=== probe loop exited rc=3 (deadline) ==="
    break
  else
    # a transient probe-loop crash must NOT end the round's watching
    echo "=== probe loop crashed rc=$rc — retrying in 60s ==="
    sleep 60
  fi
done
echo "=== watcher done after $n cycles ($(date -u +%FT%TZ)) ==="
