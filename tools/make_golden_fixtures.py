"""Generate tests/fixtures/* golden wire-format blobs INDEPENDENTLY of
paddle_tpu's serializers: the ProgramDesc/TensorDesc bytes come from the
Google protobuf runtime over the reference framework.proto (compiled with
protoc), and the tensor streams are hand-packed per the reference layout
(lod_tensor.cc:220 SerializeToStream, tensor_util.cc:385 TensorToStream).

Regenerate with:
    workdir=$(mktemp -d)
    cp <reference>/paddle/fluid/framework/framework.proto $workdir
    sed -i 's/^syntax.*$/syntax = "proto2";/' $workdir/framework.proto
    (cd $workdir && protoc --python_out=. framework.proto)
    PYTHONPATH=$workdir python tools/make_golden_fixtures.py
(the sed keeps proto2 field semantics protoc 3.21 accepts)."""
import os
import struct
import sys

import numpy as np

import framework_pb2 as ref_pb  # protoc output from reference framework.proto

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "fixtures")

FP32 = ref_pb.VarType.FP32
LOD_TENSOR = ref_pb.VarType.LOD_TENSOR

def new_program():
    pd = ref_pb.ProgramDesc()
    pd.version.version = 0
    blk = pd.blocks.add()
    blk.idx = 0
    blk.parent_idx = -1
    return pd, blk


def add_var(blk, name, shape, vtype=LOD_TENSOR, persistable=False,
            need_check_feed=False):
    v = blk.vars.add()
    v.name = name
    v.type.type = vtype
    if vtype == LOD_TENSOR:
        v.type.lod_tensor.tensor.data_type = FP32
        v.type.lod_tensor.tensor.dims.extend(shape)
    v.persistable = persistable
    v.need_check_feed = need_check_feed
    return v


def add_op(blk, type_, ins, outs, attrs=()):
    op = blk.ops.add()
    op.type = type_
    for slot, args in ins:
        iv = op.inputs.add()
        iv.parameter = slot
        iv.arguments.extend(args)
    for slot, args in outs:
        ov = op.outputs.add()
        ov.parameter = slot
        ov.arguments.extend(args)
    for name, val in attrs:
        a = op.attrs.add()
        a.name = name
        a.type = ref_pb.INT
        a.i = val
    return op


def add_fc_body(blk):
    """The shared x·W+b body both golden programs carry."""
    add_var(blk, "x", [-1, 4], need_check_feed=True)
    add_var(blk, "fc_w", [4, 3], persistable=True)
    add_var(blk, "fc_b", [3], persistable=True)
    add_var(blk, "tmp_mul", [-1, 3])
    add_var(blk, "out", [-1, 3])
    add_op(blk, "mul", [("X", ["x"]), ("Y", ["fc_w"])],
           [("Out", ["tmp_mul"])],
           [("x_num_col_dims", 1), ("y_num_col_dims", 1)])
    add_op(blk, "elementwise_add", [("X", ["tmp_mul"]), ("Y", ["fc_b"])],
           [("Out", ["out"])], [("axis", -1)])


pd, blk = new_program()
add_fc_body(blk)

os.makedirs(OUT, exist_ok=True)
with open(f"{OUT}/golden_fc.program.pb", "wb") as f:
    f.write(pd.SerializeToString())


def tensor_stream(arr, lod=()):
    """Reference LoDTensor stream: lod_tensor.cc:220 SerializeToStream +
    tensor_util.cc:385 TensorToStream."""
    parts = [struct.pack("<I", 0), struct.pack("<Q", len(lod))]
    for level in lod:
        parts.append(struct.pack("<Q", len(level) * 8))
        parts.append(np.asarray(level, np.uint64).tobytes())
    parts.append(struct.pack("<I", 0))
    desc = ref_pb.VarType.TensorDesc()
    desc.data_type = FP32
    desc.dims.extend(arr.shape)
    db = desc.SerializeToString()
    parts.append(struct.pack("<i", len(db)))
    parts.append(db)
    parts.append(np.ascontiguousarray(arr).tobytes())
    return b"".join(parts)


rng = np.random.RandomState(42)
w = rng.uniform(-1, 1, (4, 3)).astype(np.float32)
b = rng.uniform(-1, 1, (3,)).astype(np.float32)
open(f"{OUT}/golden_fc_w.tensor", "wb").write(tensor_stream(w))
open(f"{OUT}/golden_fc_b.tensor", "wb").write(tensor_stream(b))
# a ragged LoDTensor fixture exercises the LoD header path
seq = rng.uniform(-1, 1, (5, 2)).astype(np.float32)
open(f"{OUT}/golden_seq.lodtensor", "wb").write(
    tensor_stream(seq, lod=[[0, 2, 5]]))
np.savez(f"{OUT}/golden_expected.npz", w=w, b=b, seq=seq)
print("fixtures written")


# --------------------------------------------------------------------------
# golden save_inference_model DIRECTORY (reference io.py save_inference_model
# layout consumed by analysis_predictor.cc:288 — __model__ program with
# feed/fetch ops + one reference-format LoDTensor stream file per param)
# --------------------------------------------------------------------------
ipd, iblk = new_program()
add_var(iblk, "feed", [], vtype=ref_pb.VarType.FEED_MINIBATCH,
        persistable=True)
add_var(iblk, "fetch", [], vtype=ref_pb.VarType.FETCH_LIST,
        persistable=True)
# feed op first, then the shared body, then fetch — the reference
# save_inference_model op order
tmp = ref_pb.ProgramDesc()
tmp_blk = tmp.blocks.add()
add_op(tmp_blk, "feed", [("X", ["feed"])], [("Out", ["x"])], [("col", 0)])
add_fc_body(iblk)
iblk.ops.insert(0, tmp_blk.ops[0])
add_op(iblk, "fetch", [("X", ["out"])], [("Out", ["fetch"])],
       [("col", 0)])

model_dir = os.path.join(OUT, "golden_infer_model")
os.makedirs(model_dir, exist_ok=True)
with open(os.path.join(model_dir, "__model__"), "wb") as f:
    f.write(ipd.SerializeToString())
open(os.path.join(model_dir, "fc_w"), "wb").write(tensor_stream(w))
open(os.path.join(model_dir, "fc_b"), "wb").write(tensor_stream(b))
print("inference model dir written:", model_dir)
