"""Loopback PS-RPC data-plane microbench: pickle wire vs binary frames.

Starts a VarServer with an echo handler on 127.0.0.1 and sweeps payload
sizes through one VarClient per wire generation, printing MB/s for the
round trip (send + echo receive). This isolates the framing cost the
wide_deep_1b PS lane pays per tensor: the legacy wire pickles every
ndarray into the message blob (two full copies plus pickle overhead per
direction); the binary wire ships a small pickled header plus the raw
buffer via sendall(memoryview)/recv_into (docs/PS_DATA_PLANE.md).

Usage:
    python tools/rpc_microbench.py                 # 4KB..64MB sweep
    python tools/rpc_microbench.py --smoke         # tiny fast sweep (CI)

The smoke invocation is also exercised by the tier-1 suite
(tests/test_ps_data_plane.py, marker ``rpcbench``).
"""
import argparse
import os
import socket
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import numpy as np  # noqa: E402

DEFAULT_SIZES = [1 << 12, 1 << 14, 1 << 16, 1 << 18, 1 << 20, 1 << 22,
                 1 << 24, 1 << 26]
SMOKE_SIZES = [1 << 12, 1 << 16, 1 << 20]


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def run(sizes=None, repeats=5, warmup=1):
    """Returns a list of rows: {"bytes", "pickle_mb_s", "binary_mb_s",
    "speedup"} — each the round-trip goodput of an echo RPC carrying a
    float32 payload of that size."""
    from paddle_tpu.fluid.ps_rpc import VarClient, VarServer

    sizes = list(sizes or DEFAULT_SIZES)
    srv = VarServer(f"127.0.0.1:{_free_port()}",
                    {"echo": lambda value, trainer_id=0: value}).start()
    ep = f"127.0.0.1:{srv.port}"
    rows = []
    try:
        clients = {}
        old_env = os.environ.get("PADDLE_TPU_PS_PICKLE_WIRE")
        try:
            os.environ["PADDLE_TPU_PS_PICKLE_WIRE"] = "1"
            clients["pickle"] = VarClient(ep, channels=1)
            os.environ.pop("PADDLE_TPU_PS_PICKLE_WIRE", None)
            clients["binary"] = VarClient(ep, channels=1)
        finally:
            if old_env is None:
                os.environ.pop("PADDLE_TPU_PS_PICKLE_WIRE", None)
            else:
                os.environ["PADDLE_TPU_PS_PICKLE_WIRE"] = old_env
        for size in sizes:
            payload = np.arange(size // 4, dtype=np.float32)
            row = {"bytes": int(size)}
            for wire, cli in clients.items():
                for _ in range(warmup):
                    cli.call("echo", value=payload)
                t0 = time.perf_counter()
                for _ in range(repeats):
                    out = cli.call("echo", value=payload)
                dt = time.perf_counter() - t0
                assert np.asarray(out).nbytes == payload.nbytes
                # bytes cross the loopback twice per echo (there + back)
                row[f"{wire}_mb_s"] = round(
                    2 * payload.nbytes * repeats / dt / 1e6, 1)
            row["speedup"] = round(row["binary_mb_s"]
                                   / max(row["pickle_mb_s"], 1e-9), 2)
            rows.append(row)
        for cli in clients.values():
            cli.close()
    finally:
        srv.shutdown()
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny fast sweep (CI smoke)")
    ap.add_argument("--repeats", type=int, default=None)
    args = ap.parse_args(argv)
    repeats = args.repeats or (2 if args.smoke else 5)
    rows = run(sizes=SMOKE_SIZES if args.smoke else DEFAULT_SIZES,
               repeats=repeats)
    print(f"{'payload':>10} {'pickle MB/s':>12} {'binary MB/s':>12} "
          f"{'speedup':>8}")
    for r in rows:
        print(f"{r['bytes']:>10} {r['pickle_mb_s']:>12} "
              f"{r['binary_mb_s']:>12} {r['speedup']:>8}")
    return rows


if __name__ == "__main__":
    main()
