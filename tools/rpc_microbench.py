"""Loopback PS-RPC data-plane microbench: pickle wire vs binary frames.

Starts a VarServer with an echo handler on 127.0.0.1 and sweeps payload
sizes through one VarClient per wire generation, printing MB/s for the
round trip (send + echo receive). This isolates the framing cost the
wide_deep_1b PS lane pays per tensor: the legacy wire pickles every
ndarray into the message blob (two full copies plus pickle overhead per
direction); the binary wire ships a small pickled header plus the raw
buffer via sendall(memoryview)/recv_into (docs/PS_DATA_PLANE.md).

Usage:
    python tools/rpc_microbench.py                 # 4KB..64MB sweep
    python tools/rpc_microbench.py --smoke         # tiny fast sweep (CI)

The smoke invocation is also exercised by the tier-1 suite
(tests/test_ps_data_plane.py, marker ``rpcbench``).
"""
import argparse
import os
import socket
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import numpy as np  # noqa: E402

DEFAULT_SIZES = [1 << 12, 1 << 14, 1 << 16, 1 << 18, 1 << 20, 1 << 22,
                 1 << 24, 1 << 26]
SMOKE_SIZES = [1 << 12, 1 << 16, 1 << 20]
# quantized-frame sweep (docs/PS_DATA_PLANE.md "Compression"): the
# payload range where the data path is bandwidth-bound and quantization
# pays — 64KB..16MB
QUANT_SIZES = [1 << 16, 1 << 18, 1 << 20, 1 << 22, 1 << 24]


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def run(sizes=None, repeats=5, warmup=1):
    """Returns a list of rows: {"bytes", "pickle_mb_s", "binary_mb_s",
    "speedup"} — each the round-trip goodput of an echo RPC carrying a
    float32 payload of that size."""
    from paddle_tpu.fluid.ps_rpc import VarClient, VarServer

    sizes = list(sizes or DEFAULT_SIZES)
    srv = VarServer(f"127.0.0.1:{_free_port()}",
                    {"echo": lambda value, trainer_id=0: value}).start()
    ep = f"127.0.0.1:{srv.port}"
    rows = []
    try:
        clients = {}
        old_env = os.environ.get("PADDLE_TPU_PS_PICKLE_WIRE")
        try:
            os.environ["PADDLE_TPU_PS_PICKLE_WIRE"] = "1"
            clients["pickle"] = VarClient(ep, channels=1)
            os.environ.pop("PADDLE_TPU_PS_PICKLE_WIRE", None)
            clients["binary"] = VarClient(ep, channels=1)
        finally:
            if old_env is None:
                os.environ.pop("PADDLE_TPU_PS_PICKLE_WIRE", None)
            else:
                os.environ["PADDLE_TPU_PS_PICKLE_WIRE"] = old_env
        for size in sizes:
            payload = np.arange(size // 4, dtype=np.float32)
            row = {"bytes": int(size)}
            for wire, cli in clients.items():
                for _ in range(warmup):
                    cli.call("echo", value=payload)
                t0 = time.perf_counter()
                for _ in range(repeats):
                    out = cli.call("echo", value=payload)
                dt = time.perf_counter() - t0
                assert np.asarray(out).nbytes == payload.nbytes
                # bytes cross the loopback twice per echo (there + back)
                row[f"{wire}_mb_s"] = round(
                    2 * payload.nbytes * repeats / dt / 1e6, 1)
            row["speedup"] = round(row["binary_mb_s"]
                                   / max(row["pickle_mb_s"], 1e-9), 2)
            rows.append(row)
        for cli in clients.values():
            cli.close()
    finally:
        srv.shutdown()
    return rows


def run_quant(sizes=None, repeats=5, warmup=1, bandwidth_mbps=None):
    """Wire v3 quantized-frame sweep: raw (exact f32) vs fp16 vs int8
    frames through ONE loopback echo server, both directions quantized
    (request by the client flag, response by the server's — one
    process, one flag). Rows report EFFECTIVE MB/s: logical f32
    payload bytes per second, regardless of how few bytes crossed the
    wire — the number a training round actually experiences — plus the
    on-wire compression ratio from ps_rpc's byte counters.

    ``bandwidth_mbps`` emulates a thin pipe via the
    PADDLE_TPU_PS_RPC_BANDWIDTH_MBPS send throttle — the regime the
    compression claims are about. Raw loopback is CPU/syscall-bound at
    GB/s, so there quantization's codec cost can exceed the bytes it
    saves (the 1-core caveat, recorded in BENCH_LOCAL both ways)."""
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid import ps_rpc
    from paddle_tpu.fluid.ps_rpc import VarClient, VarServer

    sizes = list(sizes or QUANT_SIZES)
    old_bw = os.environ.get("PADDLE_TPU_PS_RPC_BANDWIDTH_MBPS")
    if bandwidth_mbps:
        os.environ["PADDLE_TPU_PS_RPC_BANDWIDTH_MBPS"] = \
            str(float(bandwidth_mbps))
    # the echo method must ride the data-plane quant allowlist for the
    # duration of the sweep (restored in the finally — tests call this
    # in-process and must not leak a widened allowlist)
    old_methods = ps_rpc._QUANT_METHODS
    ps_rpc._QUANT_METHODS = old_methods | {"echo"}
    srv = VarServer(f"127.0.0.1:{_free_port()}",
                    {"echo": lambda value, trainer_id=0: value}).start()
    ep = f"127.0.0.1:{srv.port}"
    rows = []
    cli = None
    old_flag = core.globals_["FLAGS_ps_wire_quant"]
    try:
        cli = VarClient(ep, channels=1)
        for size in sizes:
            rng = np.random.RandomState(0)
            payload = rng.randn(max(1, size // 256), 64).astype(
                np.float32)  # row-shaped, like embedding pulls
            row = {"bytes": int(payload.nbytes),
                   "bandwidth_mbps": (float(bandwidth_mbps)
                                      if bandwidth_mbps else None)}
            for mode in ("", "fp16", "int8"):
                core.set_flag("FLAGS_ps_wire_quant", mode)
                for _ in range(warmup):
                    cli.call("echo", value=payload)
                ps_rpc.reset_quant_wire_stats()
                t0 = time.perf_counter()
                for _ in range(repeats):
                    out = cli.call("echo", value=payload)
                dt = time.perf_counter() - t0
                assert np.asarray(out).shape == payload.shape
                key = mode or "raw"
                row[f"{key}_mb_s"] = round(
                    2 * payload.nbytes * repeats / dt / 1e6, 1)
                if mode:
                    qs = ps_rpc.quant_wire_stats()
                    row[f"{key}_wire_ratio"] = round(
                        qs["bytes_raw_total"]
                        / max(1, qs["bytes_sent_total"]), 2)
            row["fp16_speedup"] = round(
                row["fp16_mb_s"] / max(row["raw_mb_s"], 1e-9), 2)
            row["int8_speedup"] = round(
                row["int8_mb_s"] / max(row["raw_mb_s"], 1e-9), 2)
            rows.append(row)
    finally:
        ps_rpc._QUANT_METHODS = old_methods
        core.set_flag("FLAGS_ps_wire_quant", old_flag)
        if old_bw is None:
            os.environ.pop("PADDLE_TPU_PS_RPC_BANDWIDTH_MBPS", None)
        else:
            os.environ["PADDLE_TPU_PS_RPC_BANDWIDTH_MBPS"] = old_bw
        if cli is not None:
            cli.close()
        srv.shutdown()
    return rows


# spill-tier sweep (docs/PS_DATA_PLANE.md "Capacity tier"): the resident
# fractions a production hot set actually runs at
SPILL_FRACS = [1.0, 0.5, 0.25, 0.1]


def run_spill(n_rows=20000, dim=64, fracs=None, batch=2048, repeats=10,
              warmup=2, quant=""):
    """Spill-tier pull sweep: ONE in-process VarServer serving
    ``prefetch_rows`` over a LazyEmbeddingTable whose hot set is capped
    at ``frac * n_rows`` — rows-resident fraction vs effective pull
    MB/s (logical f32 row bytes per second through the served path,
    cold promotes + write-back evictions included). frac=1.0 is the
    all-in-RAM oracle lane the spilled rows are judged against.

    Uniform-random ids over the whole working set are the WORST case
    for a hot set (no skew to pin); real CTR traffic is zipfian and
    does better. On this 1-core box the loopback RPC dominates small
    batches — the sweep reports the tier's relative cost, not disk
    bandwidth."""
    import tempfile
    import threading
    from paddle_tpu.fluid import core
    from paddle_tpu.fluid.ps_rpc import VarClient, VarServer

    fracs = list(fracs or SPILL_FRACS)
    rows_bytes = batch * dim * 4
    rows_out = []
    for frac in fracs:
        hot = max(1, int(n_rows * frac))
        # the frac=1.0 oracle lane is tier-free: no tempdir to mint
        d = tempfile.mkdtemp(prefix="pt-spillbench-") \
            if frac < 1.0 else None
        tbl = core.LazyEmbeddingTable(
            height=max(n_rows, 1) * 10, dim=dim, seed=0,
            spill_path=os.path.join(d, "t.slab") if frac < 1.0 else None,
            hot_rows=hot if frac < 1.0 else None,
            at_rest_quant=quant if frac < 1.0 else "",
            spill_seg_rows=max(256, batch))
        rng = np.random.RandomState(0)
        # materialize the whole working set (spills the cold tail)
        for lo in range(0, n_rows, batch):
            tbl.get_rows(np.arange(lo, min(lo + batch, n_rows)))
        lock = threading.Lock()

        def h_prefetch(name, rows, prefetch=False, tbl=tbl, lock=lock):
            with lock:
                return tbl.get_rows(rows)

        srv = VarServer(f"127.0.0.1:{_free_port()}",
                        {"prefetch_rows": h_prefetch}).start()
        cli = VarClient(f"127.0.0.1:{srv.port}", channels=1)
        try:
            for _ in range(warmup):
                cli.call("prefetch_rows", name="t",
                         rows=rng.randint(0, n_rows, batch))
            t0 = time.perf_counter()
            for _ in range(repeats):
                out = cli.call("prefetch_rows", name="t",
                               rows=rng.randint(0, n_rows, batch))
            dt = time.perf_counter() - t0
            assert np.asarray(out).shape == (batch, dim)
            st = tbl.tier_stats()
            rows_out.append({
                "resident_frac": frac, "hot_rows": hot,
                "n_rows": n_rows, "dim": dim, "batch": batch,
                "quant": quant if frac < 1.0 else "",
                "pull_mb_s": round(rows_bytes * repeats / dt / 1e6, 1),
                "hit_rate": st.get("hit_rate", 1.0),
                "store_reads": st.get("store_reads", 0),
                "density_x": st.get("density_x", 0.0),
            })
        finally:
            cli.close()
            srv.shutdown()
            tbl.close_spill(unlink=True)
    base = rows_out[0]["pull_mb_s"] if rows_out else 1.0
    for r in rows_out:
        r["vs_resident"] = round(r["pull_mb_s"] / max(base, 1e-9), 2)
    return rows_out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny fast sweep (CI smoke)")
    ap.add_argument("--quant", action="store_true",
                    help="wire v3 quantized-frame sweep (raw vs fp16 "
                         "vs int8 effective MB/s)")
    ap.add_argument("--spill", action="store_true",
                    help="spill-tier sweep (rows-resident fraction vs "
                         "effective pull MB/s)")
    ap.add_argument("--at-rest-quant", default="",
                    help="spill sweep at-rest encoding: '' | fp16 | "
                         "int8")
    ap.add_argument("--bandwidth-mbps", type=float, default=None,
                    help="emulate a thin pipe at this many MB/s "
                         "(PADDLE_TPU_PS_RPC_BANDWIDTH_MBPS throttle)")
    ap.add_argument("--repeats", type=int, default=None)
    args = ap.parse_args(argv)
    repeats = args.repeats or (2 if args.smoke else 5)
    if args.spill:
        rows = run_spill(
            n_rows=2000 if args.smoke else 20000,
            batch=256 if args.smoke else 2048,
            repeats=repeats if args.repeats else (2 if args.smoke
                                                  else 10),
            quant=args.at_rest_quant)
        print(f"{'resident':>9} {'pull MB/s':>10} {'vs 1.0':>7} "
              f"{'hit rate':>9} {'reads':>7} {'density':>8}")
        for r in rows:
            print(f"{r['resident_frac']:>9} {r['pull_mb_s']:>10} "
                  f"{r['vs_resident']:>7} {r['hit_rate']:>9} "
                  f"{r['store_reads']:>7} {r['density_x']:>8}")
        return rows
    if args.quant:
        rows = run_quant(sizes=SMOKE_SIZES if args.smoke
                         else QUANT_SIZES, repeats=repeats,
                         bandwidth_mbps=args.bandwidth_mbps)
        print(f"{'payload':>10} {'raw MB/s':>10} {'fp16 MB/s':>10} "
              f"{'int8 MB/s':>10} {'fp16 x':>7} {'int8 x':>7}")
        for r in rows:
            print(f"{r['bytes']:>10} {r['raw_mb_s']:>10} "
                  f"{r['fp16_mb_s']:>10} {r['int8_mb_s']:>10} "
                  f"{r['fp16_speedup']:>7} {r['int8_speedup']:>7}")
        return rows
    rows = run(sizes=SMOKE_SIZES if args.smoke else DEFAULT_SIZES,
               repeats=repeats)
    print(f"{'payload':>10} {'pickle MB/s':>12} {'binary MB/s':>12} "
          f"{'speedup':>8}")
    for r in rows:
        print(f"{r['bytes']:>10} {r['pickle_mb_s']:>12} "
              f"{r['binary_mb_s']:>12} {r['speedup']:>8}")
    return rows


if __name__ == "__main__":
    main()
