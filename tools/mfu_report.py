"""MFU report from XLA's OWN cost analysis of the compiled train step
(pre-staged for the first live TPU window; reference counterpart:
operators/benchmark/op_tester.cc's measure-don't-assert discipline, plus
the BASELINE.md "≥45% MFU" bar this framework is judged against).

Instead of the hand 6·N·D FLOP formula, this lowers the FULL fluid
program (fwd+bwd+optimizer, the same _CompiledBlock step the executor
runs) and asks the compiler: `compiled.cost_analysis()["flops"]`. MFU is
then measured-time against peak. Optionally captures a profiler trace
directory for TensorBoard/XProf offline reading.

Usage:
    python -m tools.mfu_report [bert|mnist] [--trace-dir DIR]
Emits one JSON line:
    {"model": ..., "xla_flops_per_step": ..., "step_ms": ...,
     "achieved_tflops": ..., "mfu_vs_v5e_bf16_peak": ..., "backend": ...}
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

V5E_PEAK_FLOPS = 197e12  # bf16 per chip


def compiled_step_of(exe):
    """The executor's jitted step for the LAST program it ran (its
    _CompiledBlock), for lowering/cost analysis."""
    if not exe._compiled_cache:
        raise RuntimeError("run the program once before asking for its "
                           "compiled step")
    return list(exe._compiled_cache.values())[-1]


def analyze(cb, scope, feed_arrays, rng):
    """Lower the step and return XLA's cost analysis dict. Reuses the
    executor's OWN jitted step (cb._jitted), so the already-compiled
    train step is not re-compiled — on TPU that second compile would
    roughly double the tool's wall time."""
    mut = {n: scope.find_var(n).get_tensor().array for n in cb.mut_state}
    ro = {n: scope.find_var(n).get_tensor().array for n in cb.ro_state}
    lowered = cb._jitted.lower(mut, ro, feed_arrays, rng)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, (list, tuple)):  # older jax returns [dict]
        cost = cost[0]
    return cost or {}


def report(model="bert", steps=None, trace_dir=None):
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core

    backend = jax.devices()[0].platform
    smoke = backend == "cpu"
    # explicit caller args always win; defaults shrink on the CPU smoke
    steps = steps if steps is not None else (3 if smoke else 10)
    prev_bf16 = core.globals_["FLAGS_use_bf16_matmul"]
    if model == "bert":
        from paddle_tpu.models import bert
        core.set_flag("FLAGS_use_bf16_matmul", True)
        cfg = bert.bert_base_config()
        if smoke:
            cfg.update(layers=2, hidden=128, heads=2, ffn=256)
            batch, seq_len = 4, 64
        else:
            batch, seq_len = 256, 128
        main, startup, feeds, fetches = bert.build_bert_pretrain_program(
            cfg, seq_len=seq_len, dropout=0.0, lr=1e-4)

        def bert_feed(b):
            rng_np = np.random.RandomState(0)
            n_mask = max(1, int(b * seq_len * 0.15))
            return {
                "src_ids": rng_np.randint(0, cfg["vocab_size"],
                                          (b, seq_len)).astype("int64"),
                "pos_ids": np.tile(np.arange(seq_len),
                                   (b, 1)).astype("int64"),
                "sent_ids": np.zeros((b, seq_len), "int64"),
                "mask_pos": rng_np.randint(0, b * seq_len,
                                           (n_mask, 1)).astype("int64"),
                "mask_label": rng_np.randint(0, cfg["vocab_size"],
                                             (n_mask, 1)).astype("int64"),
            }

        feed = bert_feed(batch)
        fetch_list = fetches
    else:
        batch = 64
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.data("img", shape=[784], dtype="float32")
            label = fluid.data("label", shape=[1], dtype="int64")
            h = fluid.layers.fc(img, 256, act="relu")
            pred = fluid.layers.fc(h, 10, act="softmax")
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(pred, label))
            fluid.optimizer.SGD(0.01).minimize(loss)
        rng_np = np.random.RandomState(0)
        feed = {"img": rng_np.rand(batch, 784).astype("float32"),
                "label": rng_np.randint(0, 10, (batch, 1)).astype("int64")}
        fetch_list = [loss]

    from bench import _is_oom

    # OOM ladder (bench.py's): land a number, not an OOM. Every attempt
    # gets a FRESH executor+scope with startup re-run: the step is jitted
    # with donated state, so a failed run leaves the old scope's param
    # buffers deleted — retrying on it would die on "Array has been
    # deleted" instead of recovering.
    while True:
        exe = fluid.Executor()
        scope = core.Scope()
        try:
            with fluid.scope_guard(scope):
                exe.run(startup)
                exe.run(main, feed=feed, fetch_list=fetch_list,
                        return_numpy=False)  # compile + cache
            break
        except Exception as e:  # noqa: BLE001 — OOM shapes vary
            if not _is_oom(e) or model != "bert" or batch <= 8:
                raise
            batch //= 2
            print(f"mfu_report: OOM, retrying at batch {batch}",
                  file=sys.stderr)
            feed = bert_feed(batch)

    with fluid.scope_guard(scope):
        cb = compiled_step_of(exe)
        feed_arrays = {k: core._to_device_array(v)
                       for k, v in feed.items()}
        cost = analyze(cb, scope, feed_arrays, jax.random.key(0))

        def timed():
            # one dispatched scan per window (exe.run n_steps): the
            # tunnel's ~10 ms/dispatch stays out of the measured MFU;
            # the compile run below doubles as the warmup — and must be
            # SYNCED before the clock starts, or the timed dispatch
            # queues behind the still-executing warm window
            w = exe.run(main, feed=feed, fetch_list=fetch_list,
                        return_numpy=False, n_steps=steps)
            _ = np.asarray(w[0].array).ravel()[:1]
            t0 = time.perf_counter()
            o = exe.run(main, feed=feed, fetch_list=fetch_list,
                        return_numpy=False, n_steps=steps)
            _ = np.asarray(o[0].array).ravel()[:1]
            return (time.perf_counter() - t0) / steps

        try:
            if trace_dir:
                import jax.profiler
                with jax.profiler.trace(trace_dir):
                    dt = timed()
            else:
                dt = timed()
        finally:
            core.set_flag("FLAGS_use_bf16_matmul", prev_bf16)

    flops = float(cost.get("flops", 0.0))
    out = {"model": model, "xla_flops_per_step": flops,
           "step_ms": round(dt * 1e3, 3),
           "achieved_tflops": round(flops / dt / 1e12, 3) if flops else 0.0,
           "mfu_vs_v5e_bf16_peak": round(flops / dt / V5E_PEAK_FLOPS, 4)
           if flops else 0.0,
           "batch": batch, "backend": backend}
    if cost.get("bytes accessed") is not None:
        ba = float(cost["bytes accessed"])
        out["xla_bytes_accessed"] = ba
        # arithmetic intensity — below ~240 flops/byte the step is
        # HBM-bound on v5e (197e12 / 819e9)
        out["flops_per_byte"] = round(flops / ba, 2) if ba else 0.0
    if smoke:
        out["cpu_smoke"] = True
    if trace_dir:
        out["trace_dir"] = trace_dir
    return out


def main():
    model = "bert"
    trace_dir = None
    args = sys.argv[1:]
    if args and not args[0].startswith("-"):
        model = args[0]
        args = args[1:]
    if "--trace-dir" in args:
        i = args.index("--trace-dir")
        if i + 1 >= len(args) or args[i + 1].startswith("-"):
            raise SystemExit("--trace-dir requires a directory argument")
        trace_dir = args[i + 1]
    print(json.dumps(report(model, trace_dir=trace_dir)))


if __name__ == "__main__":
    main()
