#!/usr/bin/env python
"""Inspect a serialized ProgramDesc (__model__) with the native parser
(reference: the debugging several reference tools do over ProgramDesc;
backed by paddle_tpu/native/programdesc.cpp).

Usage: python tools/inspect_program.py path/to/__model__
"""
import json
import sys


def main():
    if len(sys.argv) != 2:
        raise SystemExit(__doc__)
    with open(sys.argv[1], "rb") as f:
        data = f.read()
    from paddle_tpu.native import inspect_program_bytes
    print(json.dumps(inspect_program_bytes(data), indent=2))


if __name__ == "__main__":
    main()
