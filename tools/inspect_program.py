#!/usr/bin/env python
"""Inspect a serialized ProgramDesc (__model__) with the native parser
(reference: the debugging several reference tools do over ProgramDesc;
backed by paddle_tpu/native/programdesc.cpp).

Usage: python tools/inspect_program.py path/to/__model__ [--verify]

--verify additionally runs the static-analysis plane (fluid/analysis.py,
docs/ANALYSIS.md) over the parsed program and prints each diagnostic
next to the op dump — the report JSON grows a "diagnostics" list and
each diagnosed op entry is annotated in place.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    args = [a for a in sys.argv[1:] if a != "--verify"]
    verify = "--verify" in sys.argv[1:]
    if len(args) != 1:
        raise SystemExit(__doc__)
    with open(args[0], "rb") as f:
        data = f.read()
    from paddle_tpu.native import inspect_program_bytes
    report = inspect_program_bytes(data)
    if verify:
        from tools.verify_program import verify_bytes
        _prog, _feeds, _fetches, diags = verify_bytes(data)
        report["diagnostics"] = [vars(d) for d in diags]
        # annotate the native op dump in place so a diagnostic reads
        # next to the op it fires on
        blocks = report.get("blocks") or []
        for d in diags:
            if d.op_idx is None or d.block >= len(blocks):
                continue
            ops = blocks[d.block].get("ops") or []
            if d.op_idx < len(ops) and isinstance(ops[d.op_idx], dict):
                ops[d.op_idx].setdefault("diagnostics", []).append(
                    d.format())
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
