#!/usr/bin/env python
"""Verify a saved inference model with the static-analysis plane
(fluid/analysis.py; docs/ANALYSIS.md).

Runs every verifier rule over a serialized ProgramDesc — structural
completeness (the PR 7 var-drop invariant), def-before-use, dtype/shape
propagation, dead code, distributed-protocol pairing, retrace lints —
and prints the structured diagnostics. Feed/fetch names come from the
program's own feed/fetch ops.

Usage:
    python tools/verify_program.py DIR_OR_MODEL_FILE [--level warn|error]
                                   [--json] [--strict]

DIR_OR_MODEL_FILE: a save_inference_model dir (containing __model__), a
raw __model__ file, or a fluid.save .pdmodel file.

Exit status: 0 when no error-severity diagnostics (no diagnostics at all
with --strict), 1 otherwise. --level error additionally raises the same
ProgramVerifyError the library choke points would.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_program_bytes(path: str) -> bytes:
    if os.path.isdir(path):
        for name in ("__model__", "model.pdmodel"):
            p = os.path.join(path, name)
            if os.path.exists(p):
                path = p
                break
        else:
            raise SystemExit(f"no __model__ under {path}")
    with open(path, "rb") as f:
        return f.read()


def verify_bytes(data: bytes):
    """Parse + verify; returns (program, feed_names, fetch_names,
    diagnostics). Library entry shared with the tests."""
    from paddle_tpu.fluid.framework import Program
    from paddle_tpu.fluid import analysis
    program = Program.parse_from_string(data)
    feed_names, fetch_names = [], []
    for op in program.global_block().ops:
        if op.type == "feed":
            feed_names.append(op.output("Out")[0])
        elif op.type == "fetch":
            fetch_names.append(op.input("X")[0])
    diags = analysis.verify_program(
        program, feed_names=feed_names, fetch_names=fetch_names,
        where="cli")
    return program, feed_names, fetch_names, diags


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="static verification of a saved inference model")
    ap.add_argument("path", help="save_inference_model dir or model file")
    ap.add_argument("--level", choices=("warn", "error"), default="warn",
                    help="error: raise ProgramVerifyError on "
                         "error-severity diagnostics")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable diagnostics on stdout")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on ANY diagnostic, warn-severity "
                         "included")
    args = ap.parse_args(argv)

    from paddle_tpu.fluid import analysis
    program, feeds, fetches, diags = verify_bytes(
        load_program_bytes(args.path))
    if args.json:
        print(json.dumps({
            "path": args.path, "feeds": feeds, "fetches": fetches,
            "n_blocks": program.num_blocks,
            "diagnostics": [vars(d) for d in diags]}, indent=2))
    else:
        print(f"{args.path}: {program.num_blocks} block(s), "
              f"feeds={feeds}, fetches={fetches}")
        for d in diags:
            print("  " + d.format())
        if not diags:
            print("  clean: no diagnostics")
    if args.level == "error":
        analysis.enforce(diags, level="error", where="cli")
    errors = [d for d in diags if d.severity == "error"]
    return 1 if (diags if args.strict else errors) else 0


if __name__ == "__main__":
    raise SystemExit(main())
